"""Constrained decoding: structural-JSON grammar masking.

SURVEY.md §7.4 hard-part #3: the orchestrator depends on parseable tool
calls. Prompting + defensive parsing (toolparse.py) covers the happy path;
this module adds a hard guarantee: a per-token logit mask driven by a JSON
pushdown automaton (nesting capped so the state space is finite), so a
constrained generation is always a structurally valid JSON object —
balanced containers, terminated/escaped strings, legal value starts —
ending exactly when the top-level object closes (then only stop tokens are
allowed).

The automaton is byte-level; ``TokenTable`` lifts it to any tokenizer by
simulating each vocab entry's bytes, yielding dense arrays the engine uses
ON DEVICE inside the decode block:

    allowed = token_trans[state] >= 0        # [V] mask for the next token
    state'  = token_trans[state, token]      # after sampling

Literals are matched exactly (``true``/``false``/``null``) and numbers
follow the full JSON number grammar (sign, no leading zeros, fraction,
exponent), so a constrained generation that reaches DONE always
``json.loads`` cleanly — the guarantee is total, not just structural.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import lru_cache
from typing import Iterable, Optional

import numpy as np

# modes (compound modes are tuples: (IN_NUMBER, sub) / (IN_LITERAL, rest))
START = 0  # expect '{' (or whitespace)
EXPECT_KEY = 1  # inside object: '"' or '}'
IN_KEY = 2
IN_KEY_ESC = 3
AFTER_KEY = 4  # expect ':'
EXPECT_VALUE = 5  # after ':' / '[' / ',' in array
IN_STRING = 6
IN_STRING_ESC = 7
AFTER_VALUE = 8  # expect ',' or closer
IN_NUMBER = 9  # (IN_NUMBER, sub): full JSON number DFA
IN_LITERAL = 10  # (IN_LITERAL, rest): exact true/false/null suffix
DONE = 11
EXPECT_KEY_REQ = 12  # after ',' in object: '"' only (no trailing comma)
EXPECT_VALUE_REQ = 13  # after ',' in array: value only (no trailing comma)
IN_KEY_U = 14  # (IN_KEY_U, n): n hex digits of \uXXXX left in a key
IN_STRING_U = 15  # (IN_STRING_U, n): same, in a value string

_WS = b" \t\n\r"
_DIGITS = b"0123456789"
_ESCAPABLE = b'"\\/bfnrt'  # the only legal single-char escapes
_HEX = b"0123456789abcdefABCDEF"
# EXPECT_VALUE byte -> remaining literal suffix
_LITERALS = {b"t": b"rue", b"f": b"alse", b"n": b"ull"}

OBJ, ARR = 0, 1


class JsonByteAutomaton:
    """Finite automaton over bytes: state = (mode, container stack).
    States are discovered lazily and interned to dense ids."""

    def __init__(self, max_depth: int = 8):
        self.max_depth = max_depth
        self._ids: dict[tuple, int] = {}
        self._states: list[tuple] = []
        self._trans: list[np.ndarray] = []  # per state: [256] int32 next-id or -1
        self.start = self._intern((START, ()))
        self._build()

    def _intern(self, state: tuple) -> int:
        if state not in self._ids:
            self._ids[state] = len(self._states)
            self._states.append(state)
            self._trans.append(None)  # filled by _build
        return self._ids[state]

    def _step(self, state: tuple, byte: int) -> Optional[tuple]:
        mode, stack = state
        ch = bytes([byte])

        def close_container():
            new_stack = stack[:-1]
            if not new_stack:
                return (DONE, ())
            return (AFTER_VALUE, new_stack)

        if mode == START:
            # no leading whitespace: the first sampled token must open the
            # object (whitespace here only burns the token budget)
            if ch == b"{":
                return (EXPECT_KEY, (OBJ,))
            return None
        if mode in (EXPECT_KEY, EXPECT_KEY_REQ):
            if ch in _WS:
                return state
            if ch == b'"':
                return (IN_KEY, stack)
            # '}' only legal for an EMPTY object — after a comma it would be
            # a trailing comma, which json.loads rejects
            if ch == b"}" and mode == EXPECT_KEY and stack and stack[-1] == OBJ:
                return close_container()
            return None
        if mode == IN_KEY:
            if ch == b'"':
                return (AFTER_KEY, stack)
            if ch == b"\\":
                return (IN_KEY_ESC, stack)
            if byte < 0x20:
                return None
            return state
        if mode == IN_KEY_ESC:
            if ch in _ESCAPABLE:
                return (IN_KEY, stack)
            if ch == b"u":
                return ((IN_KEY_U, 4), stack)
            return None
        if isinstance(mode, tuple) and mode[0] == IN_KEY_U:
            if ch in _HEX:
                n = mode[1] - 1
                return (IN_KEY, stack) if n == 0 else ((IN_KEY_U, n), stack)
            return None
        if mode == AFTER_KEY:
            if ch in _WS:
                return state
            if ch == b":":
                return (EXPECT_VALUE, stack)
            return None
        if mode in (EXPECT_VALUE, EXPECT_VALUE_REQ):
            if ch in _WS:
                return state
            if ch == b'"':
                return (IN_STRING, stack)
            if ch == b"{":
                if len(stack) >= self.max_depth:
                    return None
                return (EXPECT_KEY, stack + (OBJ,))
            if ch == b"[":
                if len(stack) >= self.max_depth:
                    return None
                return (EXPECT_VALUE, stack + (ARR,))
            # ']' closes only an EMPTY array (not after a comma)
            if ch == b"]" and mode == EXPECT_VALUE and stack and stack[-1] == ARR:
                return close_container()
            if ch == b"-":
                return ((IN_NUMBER, "minus"), stack)
            if ch == b"0":
                return ((IN_NUMBER, "zero"), stack)
            if ch in b"123456789":
                return ((IN_NUMBER, "int"), stack)
            if ch in _LITERALS:
                return ((IN_LITERAL, _LITERALS[ch]), stack)
            return None
        if mode == IN_STRING:
            if ch == b'"':
                return (AFTER_VALUE, stack)
            if ch == b"\\":
                return (IN_STRING_ESC, stack)
            if byte < 0x20:
                return None
            return state
        if mode == IN_STRING_ESC:
            if ch in _ESCAPABLE:
                return (IN_STRING, stack)
            if ch == b"u":
                return ((IN_STRING_U, 4), stack)
            return None
        if isinstance(mode, tuple) and mode[0] == IN_STRING_U:
            if ch in _HEX:
                n = mode[1] - 1
                return (IN_STRING, stack) if n == 0 else ((IN_STRING_U, n), stack)
            return None
        def after_value(ch):
            """',' / closer / whitespace handling shared by AFTER_VALUE and
            complete-number termination."""
            if ch in _WS:
                return (AFTER_VALUE, stack)
            if ch == b",":
                if stack and stack[-1] == OBJ:
                    return (EXPECT_KEY_REQ, stack)
                if stack and stack[-1] == ARR:
                    return (EXPECT_VALUE_REQ, stack)
                return None
            if ch == b"}" and stack and stack[-1] == OBJ:
                return close_container()
            if ch == b"]" and stack and stack[-1] == ARR:
                return close_container()
            return None

        if mode == AFTER_VALUE:
            return after_value(ch)
        if isinstance(mode, tuple) and mode[0] == IN_LITERAL:
            rest = mode[1]
            if ch == rest[:1]:
                rest = rest[1:]
                return ((IN_LITERAL, rest), stack) if rest else (AFTER_VALUE, stack)
            return None
        if isinstance(mode, tuple) and mode[0] == IN_NUMBER:
            sub = mode[1]
            if sub == "minus":  # need first digit
                if ch == b"0":
                    return ((IN_NUMBER, "zero"), stack)
                if ch in b"123456789":
                    return ((IN_NUMBER, "int"), stack)
                return None
            if sub == "frac_dot":  # '.' needs at least one digit
                return ((IN_NUMBER, "frac"), stack) if ch in _DIGITS else None
            if sub == "exp_e":  # e/E needs sign or digit
                if ch in b"+-":
                    return ((IN_NUMBER, "exp_sign"), stack)
                return ((IN_NUMBER, "exp"), stack) if ch in _DIGITS else None
            if sub == "exp_sign":
                return ((IN_NUMBER, "exp"), stack) if ch in _DIGITS else None
            # complete-number states: may extend or terminate
            if sub == "int" and ch in _DIGITS:
                return state
            if sub in ("zero", "int") and ch == b".":
                return ((IN_NUMBER, "frac_dot"), stack)
            if sub == "frac" and ch in _DIGITS:
                return state
            if sub == "exp" and ch in _DIGITS:
                return state
            if sub in ("zero", "int", "frac", "exp") and ch in b"eE":
                if sub != "exp":
                    return ((IN_NUMBER, "exp_e"), stack)
                return None
            if sub in ("zero", "int", "frac", "exp"):
                return after_value(ch)
            return None
        if mode == DONE:
            if ch in _WS:
                return state
            return None
        return None

    def _build(self) -> None:
        frontier = [0]
        while frontier:
            sid = frontier.pop()
            if self._trans[sid] is not None:
                continue
            row = np.full(256, -1, dtype=np.int32)
            state = self._states[sid]
            for byte in range(256):
                nxt = self._step(state, byte)
                if nxt is not None:
                    nid = self._intern(nxt)
                    row[byte] = nid
                    if nid >= len(self._trans) or self._trans[nid] is None:
                        while len(self._trans) < len(self._states):
                            self._trans.append(None)
                        frontier.append(nid)
            self._trans[sid] = row

    @property
    def n_states(self) -> int:
        return len(self._states)

    def is_done(self, sid: int) -> bool:
        return self._states[sid][0] == DONE

    def min_close_distances(self) -> np.ndarray:
        """[n_states] — minimum BYTES from each state to a DONE state
        (reverse BFS over the byte graph). Drives budget-aware masking: with
        k tokens left, only tokens whose next state can still close within
        k-1 are allowed, so a constrained generation ALWAYS completes inside
        its max_tokens (every closing byte is a single-byte token in
        practice: quotes, digits, braces)."""
        n = self.n_states
        rev: list[list[int]] = [[] for _ in range(n)]
        for s in range(n):
            for t in set(int(x) for x in self._trans[s] if x >= 0):
                rev[t].append(s)
        INF = np.int32(2**15 - 1)
        dist = np.full(n, INF, dtype=np.int32)
        frontier = [s for s in range(n) if self.is_done(s)]
        for s in frontier:
            dist[s] = 0
        while frontier:
            nxt_frontier = []
            for t in frontier:
                for s in rev[t]:
                    if dist[s] > dist[t] + 1:
                        dist[s] = dist[t] + 1
                        nxt_frontier.append(s)
            frontier = nxt_frontier
        return dist

    def run_bytes(self, sid: int, data: bytes) -> int:
        """-1 if the byte run is illegal from sid."""
        for b in data:
            if sid < 0:
                return -1
            sid = int(self._trans[sid][b])
        return sid


@dataclass
class TokenTable:
    """token_trans[state, token] = next state, or -1 (forbidden).
    DONE states allow only stop tokens (mapped to staying DONE)."""

    token_trans: np.ndarray  # [n_states, vocab] int32
    start_state: int
    # [n_states] min bytes to a DONE state (see min_close_distances)
    min_close: np.ndarray = None  # type: ignore[assignment]

    @property
    def n_states(self) -> int:
        return self.token_trans.shape[0]


def build_token_table(
    tokenizer,
    max_depth: int = 8,
) -> TokenTable:
    """Lift the byte automaton to the tokenizer's vocab by composing per-byte
    transition columns (vectorized over the state axis — a 128k-vocab Llama-3
    tokenizer builds in seconds, not minutes). Requires ``token_bytes(id) ->
    bytes | None`` (None = control/special token). int16 (state count is
    small) to halve the on-device table."""
    auto = JsonByteAutomaton(max_depth=max_depth)
    vocab = tokenizer.vocab_size
    stop = tokenizer.stop_tokens
    byte_trans = np.stack(auto._trans)  # [n_states, 256] int32
    n_states = auto.n_states
    assert n_states < 2**15
    done_mask = np.asarray([auto.is_done(s) for s in range(n_states)])

    table = np.full((n_states, vocab), -1, dtype=np.int16)
    ids = np.arange(n_states, dtype=np.int32)
    for tok in range(vocab):
        if tok in stop:
            # finishing is the only legal move, available exactly at DONE
            table[done_mask, tok] = ids[done_mask].astype(np.int16)
            continue
        data = tokenizer.token_bytes(tok)
        if not data:
            continue
        v = ids
        for b in data:
            v = np.where(v >= 0, byte_trans[np.clip(v, 0, None), b], -1)
        # DONE states admit no non-stop tokens (force immediate stop)
        v = np.where(done_mask, -1, v)
        table[:, tok] = v.astype(np.int16)
    return TokenTable(
        token_trans=table,
        start_state=auto.start,
        min_close=auto.min_close_distances().astype(np.int16),
    )
