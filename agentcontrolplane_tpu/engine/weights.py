"""Checkpoint loading: HF Llama weights -> our pytree, sharded at load.

The reference's llm-controller validates SaaS credentials; ours loads and
shards checkpoints (north star: "the llm-controller loads and shards HF
checkpoints across chips"). Supports:

- a directory of ``*.safetensors`` (HF format), loaded file-by-file and
  ``jax.device_put`` directly to each param's NamedSharding (never
  materializing the full model unsharded on one device);
- an in-memory HF state_dict (tests: convert a tiny random
  ``transformers.LlamaForCausalLM`` and compare logits).
"""

from __future__ import annotations

import json
import os
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..models.llama import LlamaConfig, init_params

# our pytree path -> HF tensor name (per layer where {i})
_LAYER_MAP = {
    "wq": "model.layers.{i}.self_attn.q_proj.weight",
    "wk": "model.layers.{i}.self_attn.k_proj.weight",
    "wv": "model.layers.{i}.self_attn.v_proj.weight",
    "wo": "model.layers.{i}.self_attn.o_proj.weight",
    "w1": "model.layers.{i}.mlp.gate_proj.weight",
    "w3": "model.layers.{i}.mlp.up_proj.weight",
    "w2": "model.layers.{i}.mlp.down_proj.weight",
    "ln1": "model.layers.{i}.input_layernorm.weight",
    "ln2": "model.layers.{i}.post_attention_layernorm.weight",
}
_TRANSPOSED = {"wq", "wk", "wv", "wo", "w1", "w2", "w3"}
_BIAS_MAP = {
    "bq": "model.layers.{i}.self_attn.q_proj.bias",
    "bk": "model.layers.{i}.self_attn.k_proj.bias",
    "bv": "model.layers.{i}.self_attn.v_proj.bias",
}


def config_from_hf(config_path: str) -> LlamaConfig:
    with open(config_path) as f:
        hf = json.load(f)
    is_gemma2 = hf.get("model_type") == "gemma2"
    is_gemma = hf.get("model_type") == "gemma" or is_gemma2
    act = hf.get("hidden_activation") or hf.get("hidden_act") or "silu"
    rs = hf.get("rope_scaling") or {}
    rs_type = rs.get("rope_type") or rs.get("type")
    if rs and rs_type != "llama3":
        # linear/dynamic/yarn checkpoints would silently serve the wrong
        # function — refuse at load, not at generation quality
        raise ValueError(
            f"unsupported rope_scaling type {rs_type!r} (only 'llama3')"
        )
    return LlamaConfig(
        rope_scaling_factor=float(rs.get("factor", 1.0)) if rs_type == "llama3" else 1.0,
        rope_low_freq_factor=float(rs.get("low_freq_factor", 1.0)),
        rope_high_freq_factor=float(rs.get("high_freq_factor", 4.0)),
        rope_original_max_seq=int(rs.get("original_max_position_embeddings", 8192)),
        # Mixtral: routed experts replace the dense FFN
        n_experts=int(hf.get("num_local_experts", 0) or 0),
        experts_per_token=int(hf.get("num_experts_per_tok", 2) or 2),
        vocab_size=hf["vocab_size"],
        dim=hf["hidden_size"],
        n_layers=hf["num_hidden_layers"],
        n_heads=hf["num_attention_heads"],
        n_kv_heads=hf.get("num_key_value_heads", hf["num_attention_heads"]),
        ffn_dim=hf["intermediate_size"],
        norm_eps=hf.get("rms_norm_eps", 1e-5),
        rope_theta=hf.get("rope_theta", 500000.0),
        max_seq_len=hf.get("max_position_embeddings", 8192),
        # gemma ties embeddings unconditionally
        tie_embeddings=bool(hf.get("tie_word_embeddings", is_gemma)),
        # Qwen2 checkpoints set attention_bias (or are the qwen2 model_type)
        qkv_bias=bool(hf.get("attention_bias", hf.get("model_type") == "qwen2")),
        hidden_act="gelu_tanh" if ("gelu" in act or is_gemma) else "silu",
        norm_plus_one=is_gemma,
        embed_scale=is_gemma,
        head_dim_override=hf.get("head_dim") if is_gemma else None,
        # Gemma-2: tanh soft-caps, four-norm blocks, explicit query scale,
        # alternating sliding-window layers (see LlamaConfig.sliding_window
        # for the context bound the engine enforces)
        attn_logit_softcap=float(hf.get("attn_logit_softcapping") or 0.0) if is_gemma2 else 0.0,
        final_logit_softcap=float(hf.get("final_logit_softcapping") or 0.0) if is_gemma2 else 0.0,
        post_norms=is_gemma2,
        query_pre_attn_scalar=float(hf.get("query_pre_attn_scalar") or 0.0) if is_gemma2 else 0.0,
        sliding_window=int(hf.get("sliding_window") or 0) if is_gemma2 else 0,
    )


def params_from_state_dict(
    state_dict: dict[str, Any],
    config: LlamaConfig,
    put: Optional[Callable[[str, np.ndarray], jax.Array]] = None,
    quantize: Optional[str] = None,
    lora: Optional[tuple] = None,  # (lora_params_as_numpy, LoraConfig)
) -> dict:
    """Build the params pytree from HF-named tensors.

    ``state_dict`` values may be numpy arrays or torch tensors. ``put``
    receives (pytree_path, ndarray) and returns the placed jax array —
    the seam where sharded device_put happens. With ``quantize="int8"`` the
    layer matrices are quantized HOST-SIDE before placement, so the bf16
    copy of an 8B model never touches the device (16GB-chip serving path).
    """
    from ..ops.quant import QUANTIZABLE, QuantizedTensor

    c = config
    if quantize not in (None, "int8"):
        raise ValueError(f"unsupported quantization {quantize!r}")
    if put is None:
        # quantized leaves keep their exact dtypes (int8 values, f32 scales);
        # everything else is cast to the model compute dtype
        put = lambda path, arr: jnp.asarray(
            arr,
            dtype=arr.dtype if path.endswith((".q", ".scale")) else c.dtype,
        )

    def get(name: str) -> np.ndarray:
        t = state_dict[name]
        if hasattr(t, "detach"):  # torch tensor
            t = t.detach().to("cpu").float().numpy()
        return np.asarray(t)

    params: dict = {
        "embed": put("embed", get("model.embed_tokens.weight")),
        "norm": put("norm", get("model.norm.weight")),
        "layers": {},
    }
    layer_map = dict(_LAYER_MAP)
    if c.qkv_bias:
        layer_map.update(_BIAS_MAP)
    if c.post_norms:
        # Gemma-2's four-norm block: HF's post_attention_layernorm norms the
        # attention OUTPUT (unlike llama, where that name is the pre-MLP
        # norm), and pre/post_feedforward_layernorm bracket the MLP
        layer_map["ln1_post"] = "model.layers.{i}.post_attention_layernorm.weight"
        layer_map["ln2"] = "model.layers.{i}.pre_feedforward_layernorm.weight"
        layer_map["ln2_post"] = "model.layers.{i}.post_feedforward_layernorm.weight"
    if c.n_experts > 0:
        # Mixtral: the dense MLP keys are replaced by per-expert stacks
        # (HF names the expert projections literally w1/w2/w3) + the router
        for key in ("w1", "w2", "w3"):
            layer_map.pop(key)
        layer_map["router"] = "model.layers.{i}.block_sparse_moe.gate.weight"
        layer_map.update({
            key: "model.layers.{i}.block_sparse_moe.experts.{e}." + key + ".weight"
            for key in ("w1", "w2", "w3")
        })
    for key, pattern in layer_map.items():
        mats = []
        for i in range(c.n_layers):
            if "{e}" in pattern:
                # [E, in, out] expert stack for this layer
                m = np.stack([
                    get(pattern.format(i=i, e=e)).T for e in range(c.n_experts)
                ])
            else:
                m = get(pattern.format(i=i))
                if key in _TRANSPOSED or key == "router":
                    m = m.T  # HF stores [out, in]; we compute x @ W
            mats.append(m)
        stacked = np.stack(mats)
        if lora is not None and key in lora[0]["layers"]:
            # merge the adapter HOST-SIDE, before quantization and before
            # anything reaches the device — an on-device merge of an 8B
            # model would put bf16 params + merged copies on a 16GB chip
            ab = lora[0]["layers"][key]
            stacked = stacked + np.einsum(
                "lir,lro->lio",
                np.asarray(ab["a"], dtype=np.float32),
                np.asarray(ab["b"], dtype=np.float32),
            ) * lora[1].scale
        if quantize == "int8" and key in QUANTIZABLE:
            from ..ops.quant import quantize_np

            q, scale = quantize_np(stacked)
            params["layers"][key] = QuantizedTensor(
                q=put(f"layers.{key}.q", q),
                scale=put(f"layers.{key}.scale", scale),
            )
        else:
            params["layers"][key] = put(f"layers.{key}", stacked)
    if not c.tie_embeddings:
        params["lm_head"] = put("lm_head", get("lm_head.weight").T)
    return params


def load_safetensors_dir(
    path: str,
    config: Optional[LlamaConfig] = None,
    put: Optional[Callable[[str, np.ndarray], jax.Array]] = None,
    quantize: Optional[str] = None,
    lora_path: Optional[str] = None,
) -> tuple[dict, LlamaConfig]:
    """Load an HF checkpoint directory (config.json + *.safetensors).
    ``lora_path`` merges a trained adapter (train.lora.save_lora) host-side
    BEFORE quantization/placement, so adapter+int8 serving never
    materializes an unquantized model on device."""
    from safetensors import safe_open  # lazy: not all installs ship it

    if config is None:
        config = config_from_hf(os.path.join(path, "config.json"))
    lora = None
    if lora_path is not None:
        from ..train.lora import load_lora

        lora_params, lora_cfg = load_lora(lora_path, config)
        lora = (jax.tree_util.tree_map(np.asarray, lora_params), lora_cfg)
    tensors: dict[str, np.ndarray] = {}
    for fname in sorted(os.listdir(path)):
        if not fname.endswith(".safetensors"):
            continue
        with safe_open(os.path.join(path, fname), framework="np") as f:
            for name in f.keys():
                tensors[name] = f.get_tensor(name)
    params = params_from_state_dict(tensors, config, put, quantize=quantize, lora=lora)
    return params, config


def write_synthetic_checkpoint(
    path: str,
    config: LlamaConfig,
    seed: int = 0,
    max_shard_bytes: int = 1 << 30,
) -> int:
    """Write a random-weight HF-format checkpoint (config.json +
    sharded ``*.safetensors`` + ``model.safetensors.index.json``) with the
    same tensor names, bf16 dtype, and shard layout a real Llama-3
    checkpoint ships with (values are random). Exists to close the
    no-egress verification gap — the load/quantize/shard path can be
    exercised at full Llama-3-8B scale (~16 GiB on disk) without
    downloading weights. Memory-bounded: one tensor generated at a time,
    shards flushed at ``max_shard_bytes``. Returns total bytes written.

    Plain Llama/Mistral architecture only: the qkv-bias (Qwen2), MoE
    (Mixtral) and Gemma variants need extra/renamed tensors this
    generator does not emit, and serving a silently wrong-shaped
    checkpoint would be worse than refusing."""
    import ml_dtypes
    from safetensors.numpy import save_file

    c = config
    if (
        c.qkv_bias
        or c.n_experts
        or c.head_dim_override is not None
        or c.norm_plus_one
        or c.embed_scale
        or c.hidden_act != "silu"
    ):
        raise ValueError(
            "write_synthetic_checkpoint supports the plain Llama/Mistral "
            "architecture only (no qkv_bias / MoE experts / Gemma or "
            "non-silu variants)"
        )
    hd = c.head_dim
    os.makedirs(path, exist_ok=True)
    # A rerun into the same dir must not mix generations (the loader reads
    # EVERY *.safetensors in the directory) — but NEVER clobber a real
    # checkpoint: only a dir this generator marked (config.json carries
    # "synthetic": true; unknown keys are ignored by config_from_hf) or a
    # shard-free dir may be cleared. Deleting ~16 GiB of downloaded
    # weights in a no-egress environment would be irreversible.
    existing = [f for f in os.listdir(path) if f.endswith(".safetensors")]
    if existing:
        try:
            with open(os.path.join(path, "config.json")) as f:
                marked = bool(json.load(f).get("synthetic"))
        except (OSError, json.JSONDecodeError):
            marked = False
        if not marked:
            raise ValueError(
                f"{path} contains safetensors shards not written by this "
                "generator; refusing to overwrite a (possibly real) "
                "checkpoint — pick an empty/new directory"
            )
    for f in os.listdir(path):
        if f.endswith(".safetensors") or f == "model.safetensors.index.json":
            os.unlink(os.path.join(path, f))
    hf_config: dict[str, Any] = {
        "synthetic": True,  # marks the dir as regenerable (see above)
        "model_type": "llama",
        "vocab_size": c.vocab_size,
        "hidden_size": c.dim,
        "num_hidden_layers": c.n_layers,
        "num_attention_heads": c.n_heads,
        "num_key_value_heads": c.n_kv_heads,
        "intermediate_size": c.ffn_dim,
        "rms_norm_eps": c.norm_eps,
        "rope_theta": c.rope_theta,
        "max_position_embeddings": c.max_seq_len,
        "tie_word_embeddings": c.tie_embeddings,
    }
    if c.rope_scaling_factor != 1.0:  # llama3.1/3.2-style scaled checkpoints
        hf_config["rope_scaling"] = {
            "rope_type": "llama3",
            "factor": c.rope_scaling_factor,
            "low_freq_factor": c.rope_low_freq_factor,
            "high_freq_factor": c.rope_high_freq_factor,
            "original_max_position_embeddings": c.rope_original_max_seq,
        }
    with open(os.path.join(path, "config.json"), "w") as f:
        json.dump(hf_config, f)

    # per-key HF shapes (HF stores linear weights (out, in)); NAMES come
    # from the loader's own _LAYER_MAP so generator/loader agreement is
    # structural, not a coincidence of two hand-typed lists
    hf_shape = {
        "wq": (c.n_heads * hd, c.dim),
        "wk": (c.n_kv_heads * hd, c.dim),
        "wv": (c.n_kv_heads * hd, c.dim),
        "wo": (c.dim, c.n_heads * hd),
        "w1": (c.ffn_dim, c.dim),
        "w3": (c.ffn_dim, c.dim),
        "w2": (c.dim, c.ffn_dim),
        "ln1": (c.dim,),
        "ln2": (c.dim,),
    }
    assert set(hf_shape) == set(_LAYER_MAP), "shape table drifted from _LAYER_MAP"

    def tensor_plan():
        yield "model.embed_tokens.weight", (c.vocab_size, c.dim), "normal"
        for i in range(c.n_layers):
            for key, pattern in _LAYER_MAP.items():
                kind = "ones" if key.startswith("ln") else "normal"
                yield pattern.format(i=i), hf_shape[key], kind
        yield "model.norm.weight", (c.dim,), "ones"
        if not c.tie_embeddings:
            yield "lm_head.weight", (c.vocab_size, c.dim), "normal"

    rng = np.random.default_rng(seed)
    shard: dict[str, np.ndarray] = {}
    shard_bytes = 0
    shard_files: list[str] = []  # temp names; renamed to -of- form at the end
    weight_map: dict[str, int] = {}  # tensor -> shard ordinal
    total = 0

    def flush():
        nonlocal shard, shard_bytes
        if not shard:
            return
        fname = f"model-{len(shard_files) + 1:05d}.safetensors.tmp"
        save_file(shard, os.path.join(path, fname))
        shard_files.append(fname)
        shard = {}
        shard_bytes = 0

    for name, shape, kind in tensor_plan():
        if kind == "ones":
            t = np.ones(shape, dtype=ml_dtypes.bfloat16)
        else:
            t = (rng.standard_normal(shape, dtype=np.float32) * 0.02).astype(
                ml_dtypes.bfloat16
            )
        shard[name] = t
        weight_map[name] = len(shard_files) + 1
        shard_bytes += t.nbytes
        total += t.nbytes
        if shard_bytes >= max_shard_bytes:
            flush()
    flush()

    # HF shard naming needs the total count, known only now; plus the
    # index HF's own loader requires for sharded checkpoints
    n = len(shard_files)
    final = {
        i + 1: f"model-{i + 1:05d}-of-{n:05d}.safetensors" for i in range(n)
    }
    for i, tmp in enumerate(shard_files):
        os.replace(os.path.join(path, tmp), os.path.join(path, final[i + 1]))
    with open(os.path.join(path, "model.safetensors.index.json"), "w") as f:
        json.dump({
            "metadata": {"total_size": total},
            "weight_map": {k: final[v] for k, v in weight_map.items()},
        }, f)
    return total


def sharded_init(
    config: LlamaConfig,
    key: jax.Array,
    shardings: Optional[dict] = None,
) -> dict:
    """Random params, placed per-leaf onto their shardings (benchmarks)."""
    params = init_params(config, key)
    if shardings is None:
        return params
    return jax.tree_util.tree_map(
        lambda x, s: jax.device_put(x, s), params, shardings
    )


def random_quantized_init(config: LlamaConfig, seed: int = 0) -> dict:
    """Random int8 params built HOST-SIDE tensor-by-tensor (benchmarks).

    The device-init-then-quantize path peaks at the full bf16 model plus
    one tensor — 16GB for Llama-3-8B, which alone fills a v5e chip. This
    mirrors the load-time quantization of ``params_from_state_dict``: each
    quantizable matrix is generated and quantized in host RAM and only the
    int8 values + f32 scales (plus the bf16 embeddings/norms/head) ever
    reach the device. Same pytree layout as ``models.llama.init_params``."""
    from ..ops.quant import QUANTIZABLE, QuantizedTensor

    c = config
    rng = np.random.default_rng(seed)

    def put(arr: np.ndarray, keep_dtype: bool = False) -> jax.Array:
        return jnp.asarray(arr, dtype=arr.dtype if keep_dtype else c.dtype)

    # the schema (keys, shapes, optional qkv_bias / tied-head branches) is
    # DERIVED from init_params via eval_shape — one source of truth; only
    # the per-leaf value policy (ones for norms, zeros for biases, scaled
    # normal for matrices, int8 for quantizable layer matrices) lives here
    schema = jax.eval_shape(lambda: init_params(c, jax.random.key(0)))

    def leaf(path, sds) -> Any:
        from ..ops.quant import quantize_np

        name = str(path[-1].key)
        in_layers = len(path) >= 2 and str(path[-2].key) == "layers"
        shape = sds.shape
        if name.startswith("ln") or name == "norm":
            return put(np.ones(shape, dtype=np.float32))
        if name.startswith("b"):
            return put(np.zeros(shape, dtype=np.float32))
        fan_in = shape[-1] if name == "embed" else shape[-2]
        stacked = rng.standard_normal(shape, dtype=np.float32) * fan_in**-0.5
        if in_layers and name in QUANTIZABLE:
            q, qscale = quantize_np(stacked)
            return QuantizedTensor(
                q=put(q, keep_dtype=True), scale=put(qscale, True)
            )
        return put(stacked)

    return jax.tree_util.tree_map_with_path(leaf, schema)
