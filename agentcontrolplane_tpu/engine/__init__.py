from .engine import (
    DeadlineExceededError,
    Engine,
    EngineOverloadedError,
    GenerationResult,
    SamplingParams,
)
from .tokenizer import ByteTokenizer, HFTokenizer, render_prompt, render_system
from .toolparse import parse_tool_calls, to_message
from .client import TPUEngineClient

__all__ = [
    "Engine", "GenerationResult", "SamplingParams", "ByteTokenizer",
    "HFTokenizer", "render_prompt", "render_system", "parse_tool_calls",
    "to_message", "TPUEngineClient", "EngineOverloadedError",
    "DeadlineExceededError",
]
