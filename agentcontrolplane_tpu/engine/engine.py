"""The TPU serving engine: continuous batching over a slot KV cache.

This is the in-tree replacement for the reference's delegation to LLM SaaS
(north star: "concurrent Task/ToolCall CRs are continuously batched into a
single decode stream with tensor-parallel allreduce over ICI").

Architecture:

- One **engine thread** owns the device state (params stay resident; the KV
  cache is threaded through jitted steps with donation, so XLA updates it in
  place). Requests arrive on a thread-safe queue from the asyncio control
  plane and resolve ``concurrent.futures.Future``s.
- **Admission**: a waiting request takes a free slot; its prompt is padded to
  a power-of-two bucket and run through the jitted prefill (one compiled
  program per bucket), which also samples the first token on-device.
- **Decode**: one jitted step advances ALL active slots one token and samples
  on-device — only [S] token ids cross to the host per step. Sequences join
  at prefill and leave at EOS/stop/max-tokens; the batch never drains to
  admit new work (no head-of-line blocking — SURVEY.md §7.4 hard-part #1).
- **Sharding**: params/cache carry NamedShardings over a ``('tp',)`` mesh;
  jit propagates them, XLA inserts the ICI allreduces.

The scheduler's lease interaction: the control plane's per-task lease
serializes per Task, but requests from many Tasks batch here freely — the
lease layer never serializes the engine.
"""

from __future__ import annotations

import contextlib
import hashlib
import heapq
import logging
import os
import queue
import threading
import time
import uuid
from concurrent.futures import Future, InvalidStateError
from dataclasses import dataclass, field
from typing import Any, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..models.llama import (
    LlamaConfig,
    PRESETS,
    decode_step,
    init_kv_cache,
    prefill_batch,
)
from ..observability.metrics import REGISTRY
from ..ops.paged import TRASH_PAGE
from ..ops.sampling import sample
from ..parallel.mesh import (
    kv_cache_shardings,
    param_shardings,
    serving_mesh,
)
from .tokenizer import ByteTokenizer, Tokenizer

log = logging.getLogger("acp_tpu.engine")


class EngineOverloadedError(RuntimeError):
    """The admission queue is at its configured cap: the request was shed,
    not queued. Callers should retry after ``retry_after_s`` (the REST
    layer maps this to 503 + Retry-After)."""

    def __init__(self, message: str, retry_after_s: float = 1.0):
        super().__init__(message)
        self.retry_after_s = retry_after_s


class DeadlineExceededError(RuntimeError):
    """The request's ``timeout_s`` deadline expired while it was still
    queued — it was failed fast without spending any prefill compute."""


@dataclass(frozen=True)
class SamplingParams:
    temperature: float = 0.0
    top_k: int = 0
    top_p: float = 1.0
    max_tokens: int = 512  # budget for SAMPLED tokens (forced prefix is free)
    # grammar-constrained decoding: force a structurally valid JSON object
    # (engine/constrain.py); generation ends when the object closes
    json_only: bool = False
    # teacher-forced generation prefix (token ids): prefilled with the
    # prompt, returned as part of the output, and — with json_only — the
    # constraint automaton is seeded past it. This is how tool_choice
    # "required" forces the '{"name": "X", "arguments": {' envelope so the
    # completion is guaranteed to be a parseable call to X.
    forced_prefix: tuple = ()


@dataclass
class GenerationResult:
    text: str
    tokens: list[int]
    finish_reason: str  # "stop" | "length" | "cancelled"
    prompt_tokens: int
    ttft_ms: float  # time to first token
    latency_ms: float
    # times this request was preempted (KV pool pressure) and resumed;
    # preemption is invisible in the output — this is the only trace
    preempt_count: int = 0
    # prefill/decode disaggregation (fleet/): when the request was
    # submitted with export_kv=True, the prompt's written KV rides out as
    # a HostKVEntry (rows [0, cut), page-aligned in paged mode, int8 +
    # scale twins when the cache is quantized) for a decode replica to
    # restore through inject_host_kv. None when export was skipped
    # (pool off, truncated prompt, too few rows).
    kv_handoff: Optional[object] = None


@dataclass
class _Request:
    rid: str
    prompt: list[int]
    sampling: SamplingParams
    future: Future
    # called from the ENGINE thread with each block's newly sampled token
    # ids (must not block; bridge to asyncio with call_soon_threadsafe)
    on_tokens: Optional[callable] = None
    # overlapped tool execution: called from the ENGINE thread as
    # ``(index, MessageToolCall)`` the moment a streamed tool call's braces
    # close — while the model is still decoding the rest of the turn. Must
    # not block (bridge to asyncio with call_soon_threadsafe). Set by
    # submit(on_tool_call=...), which also builds ``tool_parser``.
    on_tool_call: Optional[callable] = None
    tool_parser: Optional[object] = None  # toolparse.ToolStreamParser
    # detokenization holdback for the stream parser: token ids whose text
    # is still an incomplete UTF-8 sequence at a commit boundary
    detok_pending: list[int] = field(default_factory=list)
    # (monotonic emit time, MessageToolCall) per early-emitted call; the
    # same list object is exposed as ``future.early_tool_calls``
    early_calls: list = field(default_factory=list)
    # park-on-finish: when generation completes normally, keep the slot
    # PARKED (prompt KV resident, surplus pages released) so the next turn
    # of the same conversation — sent while this turn's tool calls execute
    # — resumes with a suffix-only prefill (see Engine._park)
    park: bool = False
    # tail-truncated prompts keep their suffix, not their prefix — they can
    # neither hit nor usefully seed the prefix cache
    truncated: bool = False
    enqueued: float = field(default_factory=time.monotonic)
    # preempt-and-resume state: tokens this request already SAMPLED (beyond
    # any forced prefix) before a preemption freed its slot. On re-admission
    # the prefill row is prompt + forced_prefix + resume_tokens, so decode
    # continues exactly where it left off — callers never see truncation.
    resume_tokens: list[int] = field(default_factory=list)
    preempt_count: int = 0
    # absolute monotonic deadline (submit's timeout_s): a request still
    # QUEUED past it is failed fast instead of wasting prefill compute
    deadline: Optional[float] = None
    # wall-clock of the FIRST first-token (survives preemption: TTFT and
    # the ttft metric are observed once per request, not once per resume)
    first_token_at: float = 0.0
    # OTLP trace linkage (SpanContext-like or {"trace_id","span_id"} dict):
    # at finish, the flight recorder exports this request's phase windows
    # as child spans under it — engine internals join the Task's trace
    trace: Optional[object] = None
    # prewarm requests skip per-request flight events and phase histograms
    # (hundreds of synthetic requests would drown the real timelines)
    prewarm: bool = False
    # fleet disaggregation: extract the prompt KV at finish and attach it
    # to the GenerationResult (see _export_kv_handoff). Mutually exclusive
    # with park — the handoff entry, not the parked slot, is the reuse unit.
    export_kv: bool = False
    # completed (True) when the request takes a slot (prefill starts).
    # Clients key their generation timeout off this, so queue wait under
    # saturation doesn't eat the per-request budget (mirrored onto
    # future.admitted by submit). A concurrent Future rather than an Event:
    # asyncio callers bridge it with wrap_future (callback-based) instead
    # of parking a default-executor thread per queued request — 64 queued
    # requests would otherwise exhaust the shared executor.
    admitted: Future = field(default_factory=Future)

    def emit(self, tokens: list[int]) -> None:
        if self.on_tokens is not None and tokens:
            try:
                self.on_tokens(tokens)
            except Exception:  # a broken consumer must not kill the engine
                self.on_tokens = None


@dataclass
class _Slot:
    request: _Request
    generated: list[int] = field(default_factory=list)
    prompt_len: int = 0
    prefix_len: int = 0  # leading forced tokens in ``generated``
    first_token_at: float = 0.0
    admit_seq: int = 0  # admission order (victim policy tie-break)
    # speculative decoding: per-slot adaptive draft-length controller
    # (engine/spec.py). Host-only — preemption saves nothing, re-admission
    # rebuilds it fresh. None when the engine runs with spec_len == 0.
    spec: Optional[object] = None
    # prompt+generated as one int32 array for the drafter, appended
    # incrementally (``generated`` only grows within a slot's lifetime;
    # re-admission builds a fresh slot). Reboxing the whole context every
    # verify dispatch would be O(ctx) host work in the decode hot loop.
    ctx_buf: Optional[np.ndarray] = None
    ctx_len: int = 0
    # parked: generation finished (future resolved) but the slot lingers
    # holding its PROMPT KV so the conversation's next turn — typically
    # arriving as soon as this turn's overlapped tool calls complete —
    # prefills only the suffix. Parked slots never decode, yield their
    # pages voluntarily under pool pressure, and expire after park_max_s.
    parked: bool = False
    parked_at: float = 0.0
    park_cut: int = 0  # KV rows valid for adoption (page-aligned in paged)
    # chunked prefill: the slot is admitted (slot id + KV pages reserved)
    # but its prompt KV is only partially written — the unified scheduler
    # advances it one chunk per dispatch cycle, interleaved with decode.
    # A prefilling slot never decodes; it is a first-class preemption
    # citizen (preempting it loses no sampled tokens — the request requeues
    # and re-enters the chunk loop from its prefix-cache start on
    # re-admission) and its deadline expiring mid-prefill releases the
    # partial KV. ``prefill_pos`` = KV rows written so far; ``prefill_row``
    # caches _full_row(request) so the hot loop doesn't rebuild it.
    prefilling: bool = False
    prefill_pos: int = 0
    prefill_row: Optional[list] = None
    # admission-time chunk-rate plan (engine/planner.py): chunks of
    # progress this slot should make per scheduler cycle so its deadline
    # is met by arithmetic, not EDF luck. Projected at admission and
    # reprojected on preempt→resume and park→adopt re-admissions; 1 for
    # deadline-free requests (exactly the PR 7 one-chunk cadence).
    chunk_quota: int = 1
    # host-tier swap-in: the HostKVEntry whose rows are being restored into
    # this slot's KV through the token-budget loop (one restore chunk per
    # scheduler cycle, budget-costed like a prefill chunk). Cleared when
    # prefill_pos reaches the entry's cut; the model prefill then resumes
    # from there. swap_stall_s accumulates the engine-thread seconds spent
    # blocked inside host->device restore copies (the host_stall phase).
    swap_entry: Optional[object] = None
    swap_stall_s: float = 0.0
    # async host-KV prefetch (host_prefetch, paged layout): the NEXT restore
    # chunk's rows, already launched host->device with non-blocking device
    # puts — {"start", "n", "groups": [(ids_dev, blocks_dev), ...]} in the
    # same pow2 page groups the blocking _swap_in_rows would scatter. The
    # commit half consumes it next cycle (scatter inside the dispatch
    # window, megastep-absorbed when fused) so the copy overlaps model
    # compute instead of stalling the engine thread. Cleared on commit,
    # fallback, abort, and swap teardown; a stale or mismatched stage is
    # discarded and the blocking path runs — byte-identical either way.
    swap_staged: Optional[dict] = None
    # cross-request shared-prefix dedup: (leader slot, leader rid, cut) —
    # this slot's rows [0, cut) are the leader's refcount-shared pages. A
    # follower admitted while its leader was still mid-prefill WAITS (no
    # chunks dispatched) until the leader has written the shared rows;
    # a leader dying mid-prefill rewinds its followers to the rows it
    # actually wrote (see _unshare_followers). None once the wait clears.
    share_of: Optional[tuple] = None


def _next_bucket(n: int, buckets: Sequence[int]) -> int:
    for b in buckets:
        if n <= b:
            return b
    return buckets[-1]


def _pow2_sizes(n: int) -> list[int]:
    """Greedy power-of-two decomposition (7 -> [4, 2, 1]) — the swap
    extract/restore dispatch sizes, so each is a bounded jit cache entry
    and no dispatch ever pads past real data (a padded write could clobber
    neighboring live KV rows)."""
    out: list[int] = []
    b = 1
    while b * 2 <= n:
        b *= 2
    while n:
        while b > n:
            b //= 2
        out.append(b)
        n -= b
    return out


def _pow2_chunks(items: list, max_chunk: int) -> list[list]:
    """Split into power-of-two-sized chunks (7 -> [4, 2, 1]) so each batch
    size is its own (bounded) jit cache entry."""
    out: list[list] = []
    i = 0
    while i < len(items):
        b = 1
        while b * 2 <= min(len(items) - i, max_chunk):
            b *= 2
        out.append(items[i : i + b])
        i += b
    return out


class Engine:
    def __init__(
        self,
        config: LlamaConfig | str = "bench-1b",
        params: Optional[dict] = None,
        tokenizer: Optional[Tokenizer] = None,
        mesh=None,
        max_slots: int = 64,
        max_ctx: int = 2048,
        prefill_buckets: Sequence[int] = (64, 128, 256, 512, 1024, 2048),
        prefill_batch_max: int = 8,  # burst admissions batch up to this many prompts
        width_buckets: Sequence[int] = (1, 2, 4, 8, 16, 32),  # low-occupancy decode widths
        prefix_cache_entries: int = 4,  # 0 disables (slot: KV copies; paged: shared pages)
        prefix_cache_max_tokens: int = 4096,  # HBM bound: total cached KV tokens
        decode_block_size: int = 8,
        kv_layout: str = "slot",  # "slot" | "paged"
        page_size: int = 16,
        kv_pages: int = 0,  # paged: total pages (0 = slot-equivalent capacity)
        # paged: how many decode blocks of pages to reserve per slot ahead of
        # need, so the block table isn't dirtied (re-uploaded) every dispatch
        page_lookahead_blocks: int = 8,
        # admission-queue cap: a submission arriving with max_queue requests
        # already waiting (submit queue + admission deque) is SHED
        # (EngineOverloadedError -> REST 503 + Retry-After) instead of
        # queueing unboundedly. 0 = unbounded (tests, embedded use).
        max_queue: int = 0,
        # chunked prefill + unified token-budget scheduler: > 0 splits every
        # prefill into chunks of at most this many tokens that CO-SCHEDULE
        # with decode blocks and speculative verify dispatches — one long
        # prompt no longer head-of-line-blocks every decoding slot for its
        # whole prefill. Greedy outputs are byte-identical chunked on vs off
        # (chunks only re-shape WHEN prompt KV is written, never what is
        # sampled). 0 = off (the default): the whole prefill runs at
        # admission, exactly the pre-chunking engine. Paged layout rounds
        # the chunk up to a page multiple (non-final chunks must commit
        # whole pages); values above the largest prefill bucket clamp to it.
        prefill_chunk: int = 0,
        # per-dispatch-cycle token budget the scheduler spends across
        # {pending prefill chunks, decode block, draft verify}. 0 = auto:
        # active_decoding_slots * decode_block_size + prefill_chunk *
        # prefilling_slots (every mid-prefill slot advances one chunk per
        # cycle while decode runs every cycle). The budget is a throttle on
        # prefill aggressiveness, not a hard gate: decode always dispatches,
        # and at least one chunk advances per cycle so neither side can
        # starve the other. Only meaningful with prefill_chunk > 0.
        token_budget: int = 0,
        # model-free speculative decoding (prompt lookup): per slot, an
        # n-gram drafter proposes up to spec_len tokens from earlier
        # occurrences in prompt + generated-so-far, and ONE batched verify
        # dispatch scores every position — accepted prefix + one corrected
        # token land per dispatch instead of one token per model step.
        # Greedy outputs are byte-identical to spec_len=0 (the accept op
        # emits the VERIFIED argmax at every position; drafts only decide
        # how many positions commit). 0 disables (the default).
        spec_len: int = 0,
        spec_ngram: int = 3,  # longest n-gram the drafter matches on
        # fused megastep dispatch: a busy chunked cycle's work — pending
        # mid-prefill chunks, final-chunk continuation prefills, and the
        # decode block (or the speculative verify pass) — compiles into ONE
        # program, so the steady-state cycle issues a single device
        # dispatch instead of 1 + #chunk-batches + #final-batches. Greedy
        # outputs are byte-identical megastep on or off (the phases are the
        # same model programs, cache-threaded in the same order; only the
        # dispatch boundary moves). False = the PR 7 split dispatches, kept
        # for A/B. Inert while nothing is mid-prefill (the plain decode /
        # verify iteration is already one dispatch).
        megastep: bool = True,
        # bound on distinct fused program shapes: a NEW (chunk bucket x
        # batch x decode width x phase-set) combination past this many
        # falls back to the split dispatches for that cycle (which reuse
        # already-compiled programs) instead of compiling yet another
        # megastep variant — fusion must not turn the jit cache into a
        # combinatorial zoo.
        megastep_max_programs: int = 32,
        # admission-time chunk-rate planner (engine/planner.py): deadline
        # requests get a per-cycle chunk quota (tokens remaining / cycles
        # until deadline) instead of the flat one-chunk-per-cycle cadence.
        # Reprojected on preempt-resume and park-adopt. Inert without
        # deadlines and under multi-host coordination (leader-local wall
        # clock, same rule as EDF ordering).
        rate_planner: bool = True,
        planner_max_quota: int = 8,  # per-slot per-cycle chunk cap
        # scheduler autopilot (engine/planner.py): every
        # autopilot_interval busy cycles, steer prefill_chunk /
        # token_budget / spec_len one bounded step from the flight
        # recorder's phase attribution + budget utilization + spec
        # acceptance. Off by default; constructor-disabled under
        # coordination (host-local wall-clock inputs would fork lockstep).
        autopilot: bool = False,
        autopilot_interval: int = 128,
        # dispatch-cycle stall watchdog: a busy cycle (fault throttles
        # included) whose wall time exceeds BOTH stall_mult x the fastest
        # cycle seen (the cadence floor) and stall_min_s records a `stall` flight
        # event + acp_engine_stalls_total — the cheap gray-failure signal
        # the fleet health state machine (fleet/health.py) consumes.
        # Observation-only: a stall never changes what is sampled.
        stall_mult: float = 8.0,
        stall_min_s: float = 0.25,
        # degradation ladder (engine/brownout.py): under sustained
        # pressure (admission sheds + watchdog stalls) step optional
        # features down in the pinned order spec_len -> park acceptance ->
        # chunk quota, one bounded rung per interval, restoring fully on
        # recovery. Off by default; constructor-disabled under
        # coordination (host-local pressure counters would fork lockstep).
        brownout: bool = False,
        brownout_interval: int = 64,
        # parked-slot lifetime: a slot parked at generation end (see
        # _Request.park) that no follow-up turn adopts within this window
        # is released. 0 disables parking entirely. Parking is also
        # disabled under multi-host coordination — the expiry decision is
        # wall-clock and would fork lockstep (same rule as deadlines).
        park_max_s: float = 30.0,
        # host-RAM KV offload tier (ops/paged.py HostKVPool): > 0 bounds a
        # host pool that preemption, park expiry, and mid-prefill deadline
        # drops swap their written KV rows into INSTEAD of discarding them
        # — re-admission swaps the rows back (a device->host->device copy)
        # rather than re-running the whole prefill. Entries are matched by
        # rid (preempt -> resume) or by token-prefix (a later request
        # re-sending the same conversation/persona). Greedy outputs are
        # byte-identical swap on or off (restored KV is a bit-exact copy of
        # what recompute would produce). 0 = off: exactly today's
        # discard-and-recompute behavior. CLI: --tpu-host-kv-bytes.
        host_kv_bytes: int = 0,
        # async host-KV prefetch (paged layout): after each restore chunk
        # commits, the NEXT chunk's rows are staged host->device with
        # non-blocking device puts so the copy overlaps model compute; the
        # scatter into pages happens inside the next cycle's dispatch
        # window (megastep-absorbed when fused). The first restore chunk
        # stays on the blocking path (it anchors the host_swap_slow/error
        # fault ordering), and any stage that is stale, mismatched, or
        # aborted by engine.prefetch_error degrades to the blocking copy —
        # byte-identical on or off; only swap_stall_s / the host_stall
        # flight phase shrink. Inert in the slot layout and when
        # host_kv_bytes=0.
        host_prefetch: bool = True,
        # cross-request shared-prefix page dedup (paged layout only): at
        # admission, a request whose page-aligned prompt prefix matches a
        # live slot's row (or an earlier member of the same admission
        # group) refcount-SHARES those prompt pages instead of allocating
        # a private copy — N concurrent tasks on one agent persona hold 1
        # copy of its pages, not N. Writes past the shared prefix go to
        # fresh pages, so decode never mutates a shared page; greedy
        # outputs are byte-identical dedup on or off. Inert in the slot
        # layout (per-slot context rows cannot be shared).
        prefix_dedup: bool = True,
        # armed runtime invariant checker (engine/invariants.py): audit the
        # engine's host-side bookkeeping — page-accounting conservation,
        # mirror counters vs recomputed truth, slot state legality — after
        # every dispatch cycle, crashing the engine on the first violation
        # instead of serving corrupt state. None reads $ACP_INVARIANTS; off
        # by default and one plain-bool branch per loop iteration when
        # disarmed (the fault seam's near-free posture).
        check_invariants: Optional[bool] = None,
        quantize: Optional[str] = None,  # "int8" = weight-only int8 serving
        # alias for quantize="int8" matching the CRD/CLI knob names
        # (--tpu-quantize-weights / LLM.spec.tpu.quantizeWeights)
        quantize_weights: bool = False,
        # int8 KV cache with per-row-per-head scales (both layouts): write
        # paths quantize on commit, attention dequantizes after the gather,
        # so a fixed HBM page/slot budget holds ~2x the tokens and the
        # host-RAM tier + shared-prefix dedup carry the quantized bytes
        # (both multipliers compound). UNLIKE every other serving knob this
        # legitimately relaxes greedy byte-identity — outputs are gated by
        # the pinned accuracy fixture (engine/accuracy.py; top-1 greedy
        # agreement + logit-MAE bounds vs the bf16 path) instead. Off (the
        # default) stays bit-for-bit identical to the pre-quantization
        # engine. CLI: --tpu-quantize-kv; CRD: LLM.spec.tpu.quantizeKv.
        quantize_kv: bool = False,
        seed: int = 0,
        # Multi-host lockstep serving (engine/coordination.py): rank 0
        # passes a CoordinationLeader (it drains the submit queue and
        # broadcasts per-iteration admission frames); other ranks pass a
        # CoordinationFollower (they replay the frame stream — their
        # submit() is disabled). None = single-host (the default).
        coordination: Optional[object] = None,
    ):
        from ..xla_cache import enable_persistent_compilation_cache

        enable_persistent_compilation_cache()
        self._coordination = coordination
        self._coord_follower = coordination is not None and hasattr(coordination, "recv")
        self.decode_block_size = max(1, decode_block_size)
        if kv_layout not in ("slot", "paged"):
            raise ValueError(f"kv_layout must be 'slot' or 'paged', got {kv_layout!r}")
        self.kv_layout = kv_layout
        self.page_size = page_size
        self.page_lookahead_blocks = max(1, page_lookahead_blocks)
        if isinstance(config, str):
            config = PRESETS[config]
        self.config = config
        self.tokenizer = tokenizer or ByteTokenizer()
        self.max_slots = max_slots
        self.max_ctx = min(max_ctx, config.max_seq_len)
        if self.max_ctx < max_ctx:
            log.warning(
                "max_ctx %d clamped to the model's max_seq_len %d — prompts "
                "beyond it are tail-truncated (and skip the prefix cache)",
                max_ctx, config.max_seq_len,
            )
        self.prefill_buckets = [b for b in prefill_buckets if b <= self.max_ctx] or [
            self.max_ctx
        ]
        self.mesh = mesh if mesh is not None else serving_mesh()
        from jax.sharding import NamedSharding, PartitionSpec as _P

        # all per-dispatch host->device uploads go through _put as
        # mesh-replicated GLOBAL arrays: identical on a single host, and
        # required for coordinated multi-host serving, where every process
        # contributes the same replicated value (a plain jnp.asarray would
        # make a process-local array that cannot mix with the mesh-global
        # cache/params in one dispatch)
        self._replicated = NamedSharding(self.mesh, _P())
        # upload guard for _put (see its docstring): identity copy that
        # breaks CPU zero-copy aliasing between numpy and XLA buffers.
        # CPU-only — TPU/GPU device_put never aliases the host buffer, and
        # the copy would transiently double device memory for the largest
        # array. Assigned before ANY _put call — __init__ uploads state.
        self._jit_upload_copy = (
            jax.jit(jnp.copy) if jax.default_backend() == "cpu" else None
        )
        tp = dict(self.mesh.shape).get("tp", 1)
        sp = dict(self.mesh.shape).get("sp", 1)
        if tp > 1 and self.config.n_kv_heads % tp:
            raise ValueError(
                f"n_kv_heads={self.config.n_kv_heads} cannot shard over tp={tp} "
                "(MQA/GQA KV heads must divide tp — serve gemma-2b-style MQA "
                "models with tp=1)"
            )
        if sp > 1:
            # context parallelism: the slot cache's ctx dim shards over sp
            # (kv_cache_specs); the paged pools shard their WITHIN-PAGE dim
            # over sp (every rank holds a 1/sp slice of every page, so page
            # gathers stay rank-local and prefix-page sharing is preserved
            # — the attention reductions keep (page, offset) unmerged and
            # compile to per-shard partials + tiny all-reduces, pinned by
            # tests/parallel/test_context_parallel_serving.py)
            if kv_layout == "paged" and self.page_size % sp:
                raise ValueError(
                    f"page_size={self.page_size} must be divisible by the "
                    f"mesh's sp={sp} for context-parallel paged serving"
                )
            if self.max_ctx % sp:
                raise ValueError(
                    f"max_ctx={self.max_ctx} must be divisible by the mesh's "
                    f"sp={sp} for context-parallel serving"
                )
        if (self.config.attn_logit_softcap or self.config.post_norms) and kv_layout == "paged":
            raise ValueError(
                "gemma-2-style models (attention soft-cap / post-norms) serve "
                "with kv_layout='slot' — the paged attention kernel has no "
                "soft-cap path"
            )
        if self.config.sliding_window and self.max_ctx > self.config.sliding_window:
            raise ValueError(
                f"max_ctx={self.max_ctx} exceeds this model's sliding window "
                f"({self.config.sliding_window}): gemma-2's alternating local "
                "layers make serving exact only within one window — lower "
                "--tpu-ctx to the window size"
            )
        self.prefill_batch_max = max(1, prefill_batch_max)
        # decode dispatch widths: smallest bucket covering the active slots
        # (each width is its own jit cache entry; keep the set small so cold
        # compiles stay bounded). max_slots is always a member.
        self.width_buckets = sorted(
            {w for w in width_buckets if 0 < w < max_slots} | {max_slots}
        )

        t0 = time.monotonic()
        if quantize not in (None, "int8"):
            raise ValueError(f"unsupported quantization {quantize!r}")
        if quantize_weights:
            quantize = "int8"
        self.quantize_kv = bool(quantize_kv)
        if params is None and quantize == "int8" and tp == 1:
            # host-side quantized random init: the device-init path below
            # peaks at the FULL bf16 model + one tensor (16GB for 8B — by
            # itself a whole v5e chip); this one only ever places int8+scales
            from .weights import random_quantized_init

            params = random_quantized_init(config, seed=seed)
        elif params is None:
            from ..models.llama import init_params as _init

            abstract = jax.eval_shape(lambda k: _init(config, k), jax.random.key(0))
            shardings = param_shardings(self.mesh, config, abstract)
            params = jax.jit(
                lambda k: _init(config, k), out_shardings=shardings
            )(jax.random.key(seed))
        if quantize == "int8":
            # Quantize per-matrix, dropping each bf16 original as its int8
            # replacement lands (in-place layer-dict mutation) so peak device
            # memory is the bf16 params + ONE extra tensor. For big
            # checkpoints prefer load-time quantization (weights.py
            # quantize="int8"), which never materializes bf16 at all; already
            # -quantized leaves are skipped here.
            from ..ops.quant import QUANTIZABLE, QuantizedTensor, quantize as _q

            layers = params["layers"]
            for key in QUANTIZABLE:
                if not isinstance(layers[key], QuantizedTensor):
                    layers[key] = jax.jit(_q)(layers[key])
        self.quantize = quantize
        self.params = params
        # per-device bytes held by weights (QuantizedTensor leaves flatten
        # to their int8 values + f32 scales, so this is the SERVED
        # footprint — the observable ~2x of quantize_weights). A sharded
        # leaf's .nbytes is the GLOBAL logical size, so sum per-shard bytes
        # per device and take the max — the per-chip HBM cost (tp-sharded
        # leaves count 1/tp per chip, replicated leaves their full size).
        # Immutable after init.
        per_device: dict = {}
        for leaf in jax.tree_util.tree_leaves(params):
            shards = getattr(leaf, "addressable_shards", None)
            if not shards:
                per_device[None] = per_device.get(None, 0) + int(
                    getattr(leaf, "nbytes", 0)
                )
            else:
                for s in shards:
                    per_device[s.device] = (
                        per_device.get(s.device, 0) + int(s.data.nbytes)
                    )
        self.weight_bytes = int(max(per_device.values(), default=0))
        REGISTRY.gauge_set(
            "acp_engine_weight_bytes", float(self.weight_bytes),
            help="per-device bytes held by model weights as served, max "
            "across local devices (int8 values + scales under "
            "quantize_weights, bf16 otherwise)",
        )
        if self.kv_layout == "paged":
            if self.max_ctx % self.page_size:
                raise ValueError(
                    f"page_size {self.page_size} must divide max_ctx {self.max_ctx}"
                )
            bad = [b for b in self.prefill_buckets if b % self.page_size]
            if bad:
                raise ValueError(
                    f"prefill buckets {bad} are not multiples of page_size {self.page_size}"
                )
            self.max_pages_per_seq = self.max_ctx // self.page_size
            self.num_pages = kv_pages or (max_slots * self.max_pages_per_seq + 1)
        self._init_kv_state()
        if self.kv_layout == "paged":
            # Compiled pallas path on real TPU (tp>1 goes through the
            # shard_map wrapper over head-sharded pages — GSPMD treats
            # pallas_call as opaque); CPU uses the exact XLA reference
            # (interpret-mode kernel equivalence is in tests). The kernel
            # targets hardware-native geometry: head_dim must be a multiple
            # of the 128-lane width (128 for llama/qwen/mistral, 256 for
            # gemma — both validated compiled-on-TPU) — Mosaic cannot
            # shape-cast the page buffer's [P, H_kv*d] -> [P, H_kv, d] split
            # for other widths (e.g. the tiny CPU-test configs), so those
            # fall back to the exact XLA gather reference.
            # sp>1 composes: each context-parallel rank runs the kernel
            # over its page slices (pos_base masking) and the unnormalized
            # (acc, m, l) states merge across ranks with one pmax + two
            # [S, H]-sized psums (paged_attention.py *_sp_sharded).
            # quantize_kv rides the kernel too: the int8 page walk DMAs the
            # f32 scale twins with each fetch and dequantizes in VMEM
            # (paged_attention.py), so the pool stays int8 in HBM and decode
            # keeps the no-gather path.
            self._use_pallas = (
                jax.default_backend() == "tpu" and config.head_dim % 128 == 0
            )
            if jax.default_backend() == "tpu" and not self._use_pallas:
                reason = "head_dim"
                log.warning(
                    "paged kv_layout on TPU without the Pallas kernel: %s; "
                    "decode uses the XLA gather reference (materializes the "
                    "gathered context every step)",
                    f"head_dim {config.head_dim} is not a multiple of 128",
                )
                # a silent perf cliff deserves a first-class signal: count
                # it and drop a flight breadcrumb so dashboards and dumps
                # show WHY decode is on the slow path (docs/observability.md).
                # The flight recorder doesn't exist yet this early in init,
                # so the event is emitted right after it is constructed.
                REGISTRY.counter_add(
                    "acp_engine_kernel_fallbacks_total",
                    1.0,
                    labels={"kernel": "paged_decode", "reason": reason},
                    help="accelerator kernel paths that fell back to the XLA "
                    "reference at engine init (kernel= which kernel, reason= "
                    "why); 0 on a healthy TPU deployment — the quantized "
                    "paged-decode path dispatches the int8 Pallas walk",
                )
                self._kernel_fallback_reason = reason
        log.info("engine init: params+cache in %.1fs", time.monotonic() - t0)

        # computed ON device (jit + out_shardings) rather than device_put so
        # the replicated key is valid under multihost meshes too
        self._rng = jax.jit(
            lambda: jax.random.key(seed), out_shardings=self._replicated
        )()
        self._queue: "queue.Queue[Optional[_Request]]" = queue.Queue()
        # admission order is strict FIFO: requests the pool can't fit yet
        # stay at the head of this deque (no starvation of large requests)
        import collections

        self._waiting: "collections.deque[_Request]" = collections.deque()
        self._outstanding: set = set()  # undone futures; failed on crash
        self._slots: dict[int, _Slot] = {}
        self._free = list(range(max_slots))
        # host mirrors of per-slot device state
        self._seq_lens = np.zeros(max_slots, dtype=np.int32)
        self._last_tokens = np.zeros(max_slots, dtype=np.int32)
        self._temps = np.zeros(max_slots, dtype=np.float32)
        self._top_ks = np.zeros(max_slots, dtype=np.int32)
        self._top_ps = np.ones(max_slots, dtype=np.float32)
        # grammar constraint: per-slot automaton state (lazy-built table)
        self._con_states = np.zeros(max_slots, dtype=np.int32)
        self._constrained = np.zeros(max_slots, dtype=bool)
        # table width = MODEL vocab (logits width); tokenizer vocab may be
        # smaller — those extra logits are simply forbidden under constraint
        # prefix KV cache (slot layout): LRU of prompt-prefix -> device KV
        # [L, cut, H_kv, d]. Agent workloads re-send growing conversations
        # with identical system prompts; a hit copies the cached KV into the
        # slot and prefills only the suffix — per-turn prefill becomes
        # O(new tokens) instead of O(whole conversation).
        import collections as _collections

        self._prefix_enabled = prefix_cache_entries > 0  # acp: mirror (immutable)
        self._prefix_cache_entries = prefix_cache_entries  # acp: mirror (immutable)
        # HBM accounting: per cached token one K+V row per layer
        # (L * H_kv * d * 2 * dtype bytes); the token bound keeps worst-case
        # cache HBM explicit instead of silently scaling with bucket sizes
        self._prefix_cache_max_tokens = prefix_cache_max_tokens
        self._prefix_cache: "_collections.OrderedDict[tuple, dict]" = (
            _collections.OrderedDict()
        )
        # engine thread mutates; stats() reads from REST threads
        self._prefix_lock = threading.Lock()
        self._jit_copy_prefix: dict[int, Any] = {}
        self._jit_extract_prefix: dict[int, Any] = {}
        self._prefix_hits = 0
        self._prefix_misses = 0
        # continuation batch sizes actually dispatched (prewarm coverage
        # is verified against this, not assumed from submit timing)
        self._cont_batch_sizes: set[int] = set()
        self._spill_batch_sizes: set[int] = set()
        self._chunk_batch_sizes: set[int] = set()  # KV-only chunk dispatches
        # plain prefill (bucket, B) pairs dispatched — each is its own
        # compiled program; prewarm's mid-batch phase verifies against this
        self._full_batch_shapes: set[tuple[int, int]] = set()
        self._token_table = None
        self._min_close = None
        self._table_lock = threading.Lock()
        self._dummy_table = self._put(np.full((1, self.config.vocab_size), -1, dtype=np.int32))
        self._dummy_min_close = self._put(np.zeros((1,), dtype=np.int32))
        # remaining sampled-token budget per slot (budget-aware constraint)
        self._budgets = np.zeros(max_slots, dtype=np.int32)
        self._thread: Optional[threading.Thread] = None
        self._stopping = False
        self._crashed = False
        self._restart_lock = threading.Lock()
        # rids whose callers abandoned the request (client timeout/disconnect);
        # slots are released at the next engine-loop iteration so orphaned
        # generations don't pin capacity to max_tokens
        self._cancelled: set[str] = set()
        # the cancel set the ENGINE LOOP consumes. Single-host it is the
        # same object as _cancelled; under coordination it holds only
        # rids that have been replicated through the frame stream, so every
        # rank applies cancels at the same iteration (lockstep).
        self._applied_cancels: set[str] = (
            self._cancelled if coordination is None else set()
        )
        self._admission_held = 0  # hold depth; see hold_admission()
        self._admission_lock = threading.Lock()  # guards the depth counter
        # device-resident decode state (see _decode_once): None until the
        # first block; _state_dirty forces a re-upload of the host mirrors
        # whenever slot assignment changed (admission/finish/cancel/restart)
        self._dev: Optional[dict] = None
        self._state_dirty = True
        self._tables_dirty = True
        self.decode_steps = 0
        self.tokens_generated = 0
        self.table_uploads = 0  # paged: block-table host->device re-uploads
        self.max_queue = max(0, max_queue)
        self.preemptions = 0  # pool-pressure preempt-and-resume events
        # chunked prefill + unified token-budget scheduler (see _dispatch_once
        # / _prefill_chunks). Both knobs are plain mutable attributes read
        # per admission/cycle so benches and tests can A/B them on one
        # engine (the chunk loop reuses the continuation programs the
        # legacy spill path already compiles).
        self.prefill_chunk = max(0, int(prefill_chunk))
        self.token_budget = max(0, int(token_budget))
        self._prefilling_count = 0  # acp: mirror — int mirror for cross-thread stats()
        self.prefill_chunks = 0  # chunk dispatches (per-slot chunks)
        self.hol_wait_s = 0.0  # decode-stall seconds attributable to prefill
        # (budget, tokens spent) last cycle — replaced atomically as a whole
        # tuple, never mutated in place, so scrape reads are torn-free
        self._budget_last = (0, 0)  # acp: mirror
        self._budget_spent_total = 0  # acp: mirror
        self._budget_total = 0  # acp: mirror
        # speculative decoding state/counters (see _decode_spec)
        self.spec_len = max(0, int(spec_len))
        self.spec_ngram = max(1, int(spec_ngram))
        self.spec_proposed = 0  # draft tokens sent to verification
        self.spec_accepted = 0  # draft tokens the model agreed with
        self.spec_dispatches = 0  # verify dispatches issued
        # fused megastep dispatch (see _megastep_dispatch). _fuse_pending
        # carries one cycle's planned-but-undispatched chunk work from
        # _prefill_chunks to the decode/verify dispatch site; it never
        # survives a cycle (every _decode_once entry consumes it).
        self.megastep = bool(megastep)
        self.megastep_max_programs = max(0, int(megastep_max_programs))  # 0 = never fuse
        self._fuse_pending: Optional[dict] = None
        self._megastep_shapes: set[tuple] = set()  # fused shapes dispatched
        self.megastep_dispatches = 0  # fused program dispatches issued
        self.megastep_fallbacks = 0  # cycles split-dispatched (shape bound)
        # admission-time chunk-rate planner + autopilot (engine/planner.py)
        from .planner import Autopilot, AutopilotLimits, CycleClock

        self.rate_planner = bool(rate_planner)
        self.planner_max_quota = max(1, int(planner_max_quota))
        self._cycle_clock = CycleClock()
        self.quota_projections = 0  # rate plans issued (admit + reproject)
        self.quota_reprojections = 0  # reprojections (resume/adopt)
        self.autopilot_enabled = bool(autopilot) and coordination is None
        self._autopilot = (  # acp: mirror (immutable; stats reads plain ints off it)
            Autopilot(
                AutopilotLimits(
                    chunk_min=self.page_size if kv_layout == "paged" else 8,
                    chunk_max=self.prefill_buckets[-1],
                    budget_max=4 * self.max_slots * self.decode_block_size
                    + 4 * self.prefill_buckets[-1],
                    spec_len_max=16,
                ),
                interval=autopilot_interval,
            )
            if self.autopilot_enabled
            else None
        )
        # gray-failure instrumentation: the dispatch watchdog + the
        # degradation ladder (see _stall_check / _brownout_tick)
        self.stall_mult = float(stall_mult)
        self.stall_min_s = float(stall_min_s)
        self.stalls = 0  # dispatch cycles the watchdog judged stalled
        self.sheds = 0  # admission sheds (bounded queue / fault site)
        self._cycle_s = 0.0  # acp: mirror — cycle EWMA snapshot for stats()
        # fastest busy cycle seen: the stall baseline. The EWMA seeds on
        # the first (compile-heavy) cycles and decays with alpha=0.1, so
        # judging against it leaves the watchdog deaf for dozens of
        # cycles after start; the min converges to honest cadence after a
        # single fast cycle and a slow cycle can never inflate it.
        self._cycle_floor = 0.0
        from .brownout import BrownoutController, BrownoutPolicy

        self.brownout_enabled = bool(brownout) and coordination is None
        self._brownout = (  # acp: mirror (immutable; stats reads plain ints off it)
            BrownoutController(BrownoutPolicy(interval=max(1, int(brownout_interval))))
            if self.brownout_enabled
            else None
        )
        self._brownout_level = 0  # acp: mirror — applied ladder rung
        self._brownout_saved: dict = {}  # knob -> pre-brownout value
        # overlapped tool execution (see _stream / _park). _parked_count is
        # a plain int mirror of "slots in _slots with parked=True" so
        # cross-thread readers (stats()) never iterate the engine-mutated
        # dict — same racy-but-safe ints-only contract as the other stats.
        self._parked_count = 0  # acp: mirror
        self.park_max_s = 0.0 if coordination is not None else max(0.0, park_max_s)
        # KV memory tiers (see _swap_out/_swap_in_rows and _collect_group's
        # dedup-leader scan). The host pool and allocator are engine-thread
        # -owned; stats() reads the mirror ints below instead.
        from ..ops.paged import HostKVPool

        self.host_kv_bytes = max(0, int(host_kv_bytes))
        self._host_pool = (
            HostKVPool(self.host_kv_bytes) if self.host_kv_bytes else None
        )
        # mutable for bench A/B (the swap-in stall scoreboard flips it
        # between runs); read per restore chunk, so a flip applies to the
        # next chunk boundary, never mid-copy
        self.host_prefetch = bool(host_prefetch)
        self.prefix_dedup = bool(prefix_dedup)
        # fleet tier (fleet/router.py): replica identity assigned at pool
        # registration — read by the fleet.replica_crash fault match in
        # _run — and the cross-thread handoff inject queue: any thread
        # enqueues HostKVEntry objects via inject_host_kv; the engine
        # thread lands them in the host pool at the top of _fill_slots,
        # BEFORE admission matching, so inject-then-submit ordering
        # guarantees the entry is visible to the submitted request.
        self.fleet_replica_id: Optional[str] = None
        self._kv_inject: "queue.Queue" = queue.Queue()
        self.kv_injects = 0  # handoff entries landed in the host pool
        self.kv_swap_outs = 0  # KV rows offloaded to the host tier (events)
        self.kv_swap_ins = 0  # host-tier restores (swap-in completions)
        self.prefix_shares = 0  # admissions that refcount-shared prompt pages
        self._host_kv_used = 0  # acp: mirror — host pool bytes in use
        self._host_kv_entries = 0  # acp: mirror — host pool entry count
        self._prefix_shared_pages = 0  # acp: mirror — pages with refcount > 1
        # jitted swap helpers, keyed by power-of-two size so compile counts
        # stay logarithmic (extract/restore decompose into pow2 chunks)
        self._jit_swap_gather: dict[int, Any] = {}  # paged: page gather
        self._jit_swap_scatter: dict[int, Any] = {}  # paged: page scatter
        self._jit_swap_extract: dict[int, Any] = {}  # slot: row slice out
        self._jit_swap_restore: dict[int, Any] = {}  # slot: row slice in
        self.tool_calls_early = 0  # calls emitted before generation ended
        self.tool_overlap_saved_s = 0.0  # sum of (finish - emit) per early call
        self.parks = 0  # slots parked at generation end
        self.park_adoptions = 0  # parked slots adopted by a follow-up turn
        self.park_releases = 0  # parked slots released (pressure/expiry/stop)
        self._admit_seq = 0  # monotonically increasing admission stamp
        # fault-injection seam (faults.FAULTS): near-free when disabled —
        # every hook is guarded by the plain-bool ``enabled`` attribute
        from ..faults import FAULTS as _faults

        self._faults = _faults
        # flight recorder (observability/flight.py): ring-buffer record of
        # every scheduler decision, always on (ACP_FLIGHT=0 disables for
        # bench A/B). Public attribute: the REST/CLI introspection surface
        # reads it via its own cross-thread-safe methods.
        from ..observability.flight import FlightRecorder

        self.flight = FlightRecorder()
        if getattr(self, "_kernel_fallback_reason", None):
            # deferred from the _use_pallas gate (the recorder didn't exist
            # yet); pairs with acp_engine_kernel_fallbacks_total
            self.flight.record(
                "kernel_fallback",
                kernel="paged_decode",
                reason=self._kernel_fallback_reason,
            )
        # compute efficiency observatory (observability/profiler.py): per-
        # dispatch program telemetry, cold-compile tracking, goodput/waste
        # ledger. Public attribute like the flight recorder: REST/CLI read
        # it via its declared cross-thread methods. ACP_PROF=0 reduces every
        # hook to one bool branch (bench A/B), and the hooks never touch
        # dispatch inputs/outputs — profiler on/off is byte-identical.
        from ..observability.profiler import DispatchProfiler

        self.profiler = DispatchProfiler(flight=self.flight)
        self.check_invariants = (
            bool(check_invariants)
            if check_invariants is not None
            else os.environ.get("ACP_INVARIANTS", "") not in ("", "0")
        )

        self._build_jitted()

    def _put(self, x) -> jax.Array:  # acp: megastep-seam — upload guard, not a model program
        if jax.process_count() > 1:
            # multihost: device_put cannot target non-addressable devices;
            # every process supplies its local shards of the same replicated
            # value (the coordination layer guarantees the values match)
            arr = np.asarray(x)
            out = jax.make_array_from_callback(
                arr.shape, self._replicated, lambda idx: arr[idx]
            )
        else:
            out = jax.device_put(x, self._replicated)
        # CPU backend: device_put may ZERO-COPY alias the host numpy buffer.
        # Feeding that alias into the donation-heavy dispatch pipeline lets
        # XLA reuse memory the Python heap also owns — observed as
        # nondeterministic greedy outputs / host-mirror corruption under
        # timing jitter. A jitted identity copy forces an XLA-owned buffer
        # (one compile per shape/dtype; shapes are bucketed and bounded).
        if self._jit_upload_copy is not None:
            return self._jit_upload_copy(out)
        return out

    # -- jitted programs -------------------------------------------------

    def _build_jitted(self):
        """Two jitted programs per layout: prefill+first-sample, and the
        K-step decode block (one dispatch advances all slots K tokens,
        amortizing host/tunnel round trips; inactive slots neither advance
        nor write; the host truncates each slot's [K] tokens at its first
        stop token). The block builder is shared across layouts — only the
        per-step cache update differs."""
        config = self.config
        NEG = jnp.float32(-1e30)

        def constrain_logits(logits, table, con_state, constrained, min_close, budget):
            """Mask logits to grammar-legal tokens for constrained slots.
            ``budget`` [S] = sampled tokens remaining INCLUDING this one:
            tokens are additionally restricted to those whose next state can
            still close the JSON within budget-1, so constrained generations
            ALWAYS complete inside max_tokens (no truncated objects)."""
            nxt = table[jnp.clip(con_state, 0, table.shape[0] - 1)]  # [S, V]
            allowed = nxt >= 0
            closable = (
                min_close[jnp.clip(nxt, 0, min_close.shape[0] - 1)]
                <= budget[:, None] - 1
            )
            budget_allowed = allowed & closable
            # if the budget is already unsatisfiable, keep plain grammar
            # legality rather than masking everything (never sample garbage)
            feasible = budget_allowed.any(axis=-1, keepdims=True)
            allowed = jnp.where(feasible, budget_allowed, allowed)
            return jnp.where(constrained[:, None] & ~allowed, NEG, logits)

        def advance_constraint(table, con_state, constrained, toks):
            nxt = table[jnp.clip(con_state, 0, table.shape[0] - 1), toks]
            return jnp.where(constrained, nxt, con_state)

        def sample_first(logits, rng, temps, top_ks, top_ps, table, con_states, constrained, min_close, budgets):
            """Constrained sampling for a [B] batch of first tokens."""
            logits = constrain_logits(logits, table, con_states, constrained, min_close, budgets)
            toks = sample(logits, rng, temps, top_ks, top_ps)
            new_states = advance_constraint(table, con_states, constrained, toks)
            return toks, new_states

        def make_decode_block(step_fn):
            # trace-time constants: finish detection runs ON DEVICE so decode
            # blocks can chain device-resident state (see _decode_once) —
            # a slot that samples a stop token, exhausts its budget, or hits
            # the context edge deactivates itself mid-block and stops
            # advancing/writing, keeping the device state consistent with the
            # host's bookkeeping without a per-block re-upload.
            stop_toks = tuple(sorted({int(t) for t in self.tokenizer.stop_tokens}))
            max_ctx = self.max_ctx

            def decode_block(
                params, cache, tokens, seq_lens, active, rng, temps, top_ks, top_ps,
                table, con_states, constrained, min_close, budgets, *extra,
            ):
                def step(carry, _):
                    cache, tokens, seq_lens, con_states, budgets, active, rng = carry
                    rng, sub = jax.random.split(rng)
                    cache, logits = step_fn(params, cache, tokens, seq_lens, active, *extra)
                    logits = constrain_logits(
                        logits, table, con_states, constrained, min_close, budgets
                    )
                    next_toks = sample(logits, sub, temps, top_ks, top_ps)
                    next_toks = jnp.where(active, next_toks, tokens)
                    con_states = advance_constraint(table, con_states, constrained, next_toks)
                    seq_lens = seq_lens + active.astype(jnp.int32)
                    budgets = budgets - active.astype(jnp.int32)
                    is_stop = jnp.zeros_like(active)
                    for st in stop_toks:
                        is_stop = is_stop | (next_toks == st)
                    active = active & ~is_stop & (budgets > 0) & (seq_lens + 1 < max_ctx)
                    return (cache, next_toks, seq_lens, con_states, budgets, active, rng), next_toks

                (cache, tokens, seq_lens, con_states, budgets, active, rng), toks = jax.lax.scan(
                    step, (cache, tokens, seq_lens, con_states, budgets, active, rng), None,
                    length=self.decode_block_size,
                )
                return cache, toks, (tokens, seq_lens, con_states, budgets, active, rng)

            # raw (unjitted): the split path jits it standalone; the fused
            # megastep composes the same body so both paths trace the same
            # graph per phase
            return decode_block

        def make_verify(verify_fn):
            """Speculative verify + on-device accept in one dispatch: the
            multi-token continuation machinery scores every draft position,
            then ``speculative_accept`` walks them with the SAME constraint
            masking / stop / budget semantics as the decode block — greedy
            emission at every position is the verified argmax, so spec-on
            greedy output is byte-identical to spec-off. One fetch returns
            (tokens, emitted counts, constraint states)."""
            from ..ops.sampling import speculative_accept

            stop_toks = tuple(sorted({int(t) for t in self.tokenizer.stop_tokens}))

            def verify_block(
                params, cache, inputs, n_input, starts, active, rng, temps,
                top_ks, top_ps, table, con_states, constrained, min_close,
                budgets, force_reject, *extra,
            ):
                cache, logits = verify_fn(params, cache, inputs, n_input, starts, *extra)
                out_toks, n_emit, new_states = speculative_accept(
                    logits, inputs, n_input, active, rng, temps, top_ks,
                    top_ps, stop_toks, budgets, force_reject,
                    constrain_fn=lambda l, s, b: constrain_logits(
                        l, table, s, constrained, min_close, b
                    ),
                    advance_fn=lambda s, t, take: jnp.where(
                        take, advance_constraint(table, s, constrained, t), s
                    ),
                    con_states=con_states,
                )
                return cache, out_toks, n_emit, new_states

            return verify_block  # raw; jitted standalone AND fused below

        def make_megastep(mid_fn, final_fn, decode_block, verify_block,
                          plain_fn=None):
            """The fused per-cycle program (see _megastep_dispatch): one
            compiled dispatch runs [staged swap-in scatters] -> [mid-chunk
            KV writes] -> [plain full-prompt prefill + first-token sample]
            -> [final-chunk continuation prefill + first-token sample] ->
            [decode block | speculative verify], with the cache threaded
            phase to phase so the write/read ordering is exactly the split
            path's dispatch order. Each phase is the SAME raw body the
            split programs jit standalone (the swaps phase is literally
            _swap_in_rows' scatter expression; plains run the plain causal
            program's raw body, byte-for-byte the chunked-off dispatch),
            so per-phase math is identical and greedy outputs stay byte-
            identical. Absent phases pass None (an empty pytree: presence
            is part of the trace, so every phase combination is its own
            compiled shape — bounded by megastep_max_programs). swaps is a
            tuple of (page_ids, blocks) pow2 scatter groups; the restored
            slots' pages are disjoint from every other phase's (page
            ownership is per-slot), so phase order among the prefill
            phases cannot change bytes. Donation: the cache and the decode
            carry arrays, matching the split decode block's in-place
            reuse; dec_aux (temps/top_ks/table/...) is host-retained
            across blocks and must NOT donate. plain_fn is None in the
            slot layout — plains/swaps only absorb under paged KV (their
            padding lanes need TRASH_PAGE routing to stay harmless)."""

            def megastep(params, cache, swaps, mids, plains, finals,
                         dec_carry, dec_aux, ver):
                p_out = f_out = d_out = v_out = None
                if swaps is not None:
                    for s_ids, s_blocks in swaps:
                        cache = {
                            name: cache[name].at[:, s_ids].set(s_blocks[name])
                            for name in cache
                        }
                if mids is not None:
                    cache = mid_fn(params, cache, *mids)
                if plains is not None:
                    lanes, (p_rng, p_temps, p_top_ks, p_top_ps, p_table,
                            p_con0, p_cst0, p_minc, p_budg) = plains
                    cache, logits = plain_fn(params, cache, *lanes)
                    p_out = sample_first(
                        logits, p_rng, p_temps, p_top_ks, p_top_ps, p_table,
                        p_con0, p_cst0, p_minc, p_budg,
                    )
                if finals is not None:
                    lanes, (f_rng, f_temps, f_top_ks, f_top_ps, f_table,
                            f_con0, f_cst0, f_minc, f_budg) = finals
                    cache, logits = final_fn(params, cache, *lanes)
                    f_out = sample_first(
                        logits, f_rng, f_temps, f_top_ks, f_top_ps, f_table,
                        f_con0, f_cst0, f_minc, f_budg,
                    )
                if dec_carry is not None:
                    tokens, seq_lens, con_states, budgets, active, rng = dec_carry
                    temps, top_ks, top_ps, table, constrained, min_close, extra = dec_aux
                    cache, toks, carry = decode_block(
                        params, cache, tokens, seq_lens, active, rng, temps,
                        top_ks, top_ps, table, con_states, constrained,
                        min_close, budgets, *extra,
                    )
                    d_out = (toks, carry)
                if ver is not None:
                    cache, out_toks, n_emit, new_states = verify_block(
                        params, cache, *ver
                    )
                    v_out = (out_toks, n_emit, new_states)
                return cache, p_out, f_out, d_out, v_out

            return jax.jit(megastep, donate_argnums=(1, 6))

        if self.kv_layout == "paged":
            from ..models.llama import (
                decode_step_paged,
                prefill_paged_batch,
                prefill_paged_continue,
            )

            use_pallas = self._use_pallas

            def prefill_and_sample(params, pages, tokens, lengths, page_ids, rng, temps, top_ks, top_ps, table, con_states, constrained, min_close, budgets):
                pages, logits = prefill_paged_batch(params, pages, tokens, lengths, page_ids, config)
                toks, states = sample_first(logits, rng, temps, top_ks, top_ps, table, con_states, constrained, min_close, budgets)
                return pages, toks, states

            self._jit_prefill_paged = jax.jit(prefill_and_sample, donate_argnums=(1,))

            def paged_continue_and_sample(params, pages, tokens, lengths, starts, page_ids, block_tables, rng, temps, top_ks, top_ps, table, con_states, constrained, min_close, budgets):
                pages, logits = prefill_paged_continue(
                    params, pages, tokens, lengths, starts, page_ids, block_tables, config
                )
                toks, states = sample_first(logits, rng, temps, top_ks, top_ps, table, con_states, constrained, min_close, budgets)
                return pages, toks, states

            self._jit_prefill_paged_continue = jax.jit(
                paged_continue_and_sample, donate_argnums=(1,)
            )
            mesh = self.mesh
            decode_block = make_decode_block(
                lambda params, pages, tokens, seq_lens, active, block_tables: decode_step_paged(
                    params, pages, tokens, seq_lens, block_tables, active, config,
                    use_pallas=use_pallas, mesh=mesh,
                )
            )
            self._jit_decode_paged = jax.jit(
                decode_block, donate_argnums=(1, 2, 3, 4, 5, 10, 13)
            )
            from ..models.llama import verify_paged_continue

            verify_block = make_verify(
                lambda params, pages, inputs, n_input, starts, block_tables: verify_paged_continue(
                    params, pages, inputs, n_input, starts, block_tables, config
                )
            )
            self._jit_verify = jax.jit(verify_block, donate_argnums=(1,))
            from ..models.llama import prefill_paged_continue_kv

            self._jit_megastep = make_megastep(
                lambda params, pages, toks, lens, starts, page_ids, tables: (
                    prefill_paged_continue_kv(
                        params, pages, toks, lens, starts, page_ids, tables, config
                    )
                ),
                lambda params, pages, toks, lens, starts, page_ids, tables: (
                    prefill_paged_continue(
                        params, pages, toks, lens, starts, page_ids, tables, config
                    )
                ),
                decode_block,
                verify_block,
                plain_fn=lambda params, pages, toks, lens, page_ids: (
                    prefill_paged_batch(params, pages, toks, lens, page_ids, config)
                ),
            )
        else:

            def prefill_and_sample(params, cache, tokens, lengths, slots, rng, temps, top_ks, top_ps, table, con_states, constrained, min_close, budgets):
                cache, logits = prefill_batch(params, cache, tokens, lengths, slots, config)
                toks, states = sample_first(logits, rng, temps, top_ks, top_ps, table, con_states, constrained, min_close, budgets)
                return cache, toks, states

            self._jit_prefill = jax.jit(prefill_and_sample, donate_argnums=(1,))

            from ..models.llama import prefill_continue

            def continue_and_sample(params, cache, tokens, lengths, starts, slots, rng, temps, top_ks, top_ps, table, con_states, constrained, min_close, budgets):
                cache, logits = prefill_continue(
                    params, cache, tokens, lengths, starts, slots, config
                )
                toks, states = sample_first(logits, rng, temps, top_ks, top_ps, table, con_states, constrained, min_close, budgets)
                return cache, toks, states

            self._jit_prefill_continue = jax.jit(continue_and_sample, donate_argnums=(1,))
            decode_block = make_decode_block(
                lambda params, cache, tokens, seq_lens, active: decode_step(
                    params, cache, tokens, seq_lens, config, active=active
                )
            )
            self._jit_decode = jax.jit(
                decode_block, donate_argnums=(1, 2, 3, 4, 5, 10, 13)
            )
            from ..models.llama import verify_continue

            verify_block = make_verify(
                lambda params, cache, inputs, n_input, starts: verify_continue(
                    params, cache, inputs, n_input, starts, config
                )
            )
            self._jit_verify = jax.jit(verify_block, donate_argnums=(1,))
            from ..models.llama import prefill_continue_kv

            self._jit_megastep = make_megastep(
                lambda params, cache, toks, lens, starts, slots_: (
                    prefill_continue_kv(
                        params, cache, toks, lens, starts, slots_, config
                    )
                ),
                lambda params, cache, toks, lens, starts, slots_: (
                    prefill_continue(
                        params, cache, toks, lens, starts, slots_, config
                    )
                ),
                decode_block,
                verify_block,
            )

    # -- public API ------------------------------------------------------

    def _init_kv_state(self) -> None:
        """(Re)build the device KV cache and host allocator state — shared
        by __init__ and crash recovery (ensure_running) so the restart path
        can never diverge from fresh construction."""
        self._dev = None
        self._state_dirty = True
        self._tables_dirty = True
        if self.kv_layout == "slot":
            self.cache = jax.jit(  # acp: donated
                lambda: init_kv_cache(
                    self.config, self.max_slots, self.max_ctx,
                    quantize_kv=self.quantize_kv,
                ),
                out_shardings=kv_cache_shardings(self.mesh, self.quantize_kv),
            )()
        else:
            from jax.sharding import NamedSharding, PartitionSpec as P

            from ..models.llama import init_paged_cache
            from ..ops.paged import PageAllocator

            sp_axis = (
                "sp"
                if "sp" in self.mesh.axis_names and dict(self.mesh.shape)["sp"] > 1
                else None
            )
            # [L, num_pages, page_size, H_kv, d]: heads over tp; within-page
            # over sp (context-parallel paged serving — page ids stay
            # rank-local, each rank holds a slice of every page)
            page_spec = P(None, None, sp_axis, "tp", None)
            page_shardings = {
                "k": NamedSharding(self.mesh, page_spec),
                "v": NamedSharding(self.mesh, page_spec),
            }
            if self.quantize_kv:
                # scale twins [L, NP, P, H_kv]: value spec minus head_dim
                scale_spec = NamedSharding(self.mesh, P(None, None, sp_axis, "tp"))
                page_shardings["ks"] = scale_spec
                page_shardings["vs"] = scale_spec
            self.cache = jax.jit(  # acp: donated
                lambda: init_paged_cache(
                    self.config, self.num_pages, self.page_size,
                    quantize_kv=self.quantize_kv,
                ),
                out_shardings=page_shardings,
            )()
            self._allocator = PageAllocator(
                self.num_pages, track_scales=self.quantize_kv
            )
            self._slot_pages: dict[int, list[int]] = {}
            self._block_tables = np.full(
                (self.max_slots, self.max_pages_per_seq), TRASH_PAGE, dtype=np.int32
            )

    def start(self) -> None:
        if self._thread is not None:
            return
        self._stopping = False
        self._thread = threading.Thread(target=self._run, name="tpu-engine", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        # the restart lock serializes against an in-flight crash recovery;
        # clearing _crashed makes a deliberate stop final (no resurrection
        # by a late ensure_running)
        with self._restart_lock:
            self._crashed = False
            if self._thread is None:
                return
            self._stopping = True
            self._queue.put(None)
            if self._coord_follower:
                # the loop may be parked in recv(); closing the channel
                # unblocks it, and _admit treats it as a clean stop
                self._coordination.close()
            self._thread.join(timeout=30)
            self._thread = None

    def ensure_running(self) -> bool:
        """Crash recovery (the phase-machine-and-requeue posture of the
        control plane, applied to the data plane): if the engine loop died
        on an exception — NOT a user stop() — rebuild the device-side
        serving state (KV cache, page tables, slot bookkeeping; params are
        untouched) and restart the loop. Callers' failed requests were
        already resolved with errors; the control plane's 5s requeue then
        retries them against the recovered engine. Returns True when the
        engine is running."""
        with self._restart_lock:
            if self._crashed:
                # the crashed thread may still be draining; the restart must
                # own the loop exclusively, so a wedged drain defers recovery
                # to the caller's next retry rather than racing it
                if self._thread is not None:
                    self._thread.join(timeout=30)
                    if self._thread.is_alive():
                        log.error("crashed engine thread still draining; deferring restart")
                        return False
            elif self._thread is not None and self._thread.is_alive():
                return True
            else:
                return False  # deliberately stopped; stay stopped
            log.warning("engine crashed; rebuilding serving state and restarting")
            self._init_kv_state()
            self._slots = {}
            self._parked_count = 0
            self._prefilling_count = 0
            self._publish_park_gauge()
            self._free = list(range(self.max_slots))
            self._waiting.clear()
            self._cancelled.clear()
            self._applied_cancels.clear()
            self._seq_lens[:] = 0
            self._last_tokens[:] = 0
            self._con_states[:] = 0
            self._constrained[:] = False
            self._budgets[:] = 0
            with self._prefix_lock:
                self._prefix_cache.clear()  # entries reference the old arrays only; safe either way
            # host-tier entries SURVIVE a crash rebuild: they are token-
            # derived KV copies, valid against the fresh cache — a
            # control-plane retry of a failed request prefix-matches them
            self._publish_memory_state()
            self._crashed = False
            self._stopping = False
            self._thread = threading.Thread(target=self._run, name="tpu-engine", daemon=True)
            self._thread.start()
            REGISTRY.counter_add("acp_engine_restarts_total", 1.0)
            self.flight.record("restart")
            return True

    def submit(
        self,
        prompt: str | list[int],
        sampling: Optional[SamplingParams] = None,
        on_tokens=None,
        timeout_s: Optional[float] = None,
        on_tool_call=None,
        park: bool = False,
        trace=None,
        _prewarm: bool = False,
        export_kv: bool = False,
    ) -> Future:
        """Thread-safe; returns a Future[GenerationResult]. ``on_tokens``
        (optional) streams newly sampled token ids per decode block from the
        engine thread — keep it non-blocking. ``timeout_s`` propagates the
        caller's deadline into the admission queue: a request still queued
        when it expires fails fast (DeadlineExceededError) without wasting
        prefill. ``_prewarm`` requests bypass the prefix cache entirely (no
        entries, no counters) and are exempt from the queue cap.

        Overlapped tool execution: ``on_tool_call`` is invoked from the
        engine thread as ``(index, MessageToolCall)`` the moment a streamed
        tool call's closing brace is decoded — while the model is still
        generating — so callers can start executing it immediately. The
        emitted calls (with timestamps) are also exposed on the returned
        future as ``early_tool_calls``. ``park=True`` keeps the slot parked
        after a normal finish so the conversation's next turn prefills only
        its suffix (see docs/serving-engine.md "Overlapped tool
        execution"). Neither knob changes WHAT is generated — greedy output
        is byte-identical with them on or off.

        ``export_kv=True`` (fleet prefill/decode disaggregation) extracts
        the prompt's written KV at finish and attaches it to the result as
        ``GenerationResult.kv_handoff`` — a ``HostKVEntry`` a decode
        replica restores via :meth:`inject_host_kv`. Export supersedes
        parking (the entry, not the slot, is the reuse unit)."""
        tokens = self.tokenizer.encode(prompt) if isinstance(prompt, str) else list(prompt)
        s = sampling or SamplingParams()
        prefix_len = len(s.forced_prefix)
        # keep the prompt's TAIL and reserve room to actually generate —
        # otherwise a context-filling prompt leaves a 1-token budget and
        # every response (and any forced tool call) truncates immediately
        reserve = min(s.max_tokens, max(1, self.max_ctx // 2))
        budget = max(1, self.max_ctx - prefix_len - reserve)
        truncated = len(tokens) > budget or _prewarm
        if len(tokens) > budget:
            tokens = tokens[-budget:]
        req = _Request(
            rid=uuid.uuid4().hex[:8],
            prompt=tokens,
            sampling=sampling or SamplingParams(),
            future=Future(),
            on_tokens=on_tokens,
            truncated=truncated,
            deadline=(time.monotonic() + timeout_s) if timeout_s else None,
            on_tool_call=on_tool_call,
            # truncated prompts keep their suffix, not their prefix: the
            # next turn's prompt can never extend them, so parking would
            # pin pages that no adoption can ever use
            park=bool(park) and self.park_max_s > 0 and not truncated
            and not export_kv,
            trace=trace,
            prewarm=bool(_prewarm),
            export_kv=bool(export_kv) and not _prewarm,
        )
        if on_tool_call is not None:
            from .toolparse import ToolStreamParser

            req.tool_parser = ToolStreamParser()
        req.future.early_tool_calls = req.early_calls  # type: ignore[attr-defined]
        # rid rides the future from birth — cancel() keys on it, and a shed
        # request's flight timeline is only findable through it
        req.future.rid = req.rid  # type: ignore[attr-defined]
        if self._coord_follower:
            # any locally-originated request (prewarm included) would break
            # lockstep — followers only replay the leader's frame stream
            req.future.set_exception(RuntimeError(
                "coordinated follower engines do not accept submissions "
                "(submit through rank 0's engine)"
            ))
            return req.future
        if self._thread is None or self._stopping:
            req.future.set_exception(RuntimeError("engine is not running"))
            return req.future
        if not _prewarm:
            # persona fingerprint: the same first-64-token hash the fleet
            # router keys affinity on, so single-engine trace export
            # (observability/trace_export.py) captures the prefix-sharing
            # mix without retaining any prompt content
            persona = hashlib.sha1(
                repr(tokens[:64]).encode()
            ).hexdigest()[:16] if self.flight.enabled else ""
            self.flight.record(
                "submit", rid=req.rid, prompt_tokens=len(tokens),
                timeout_s=timeout_s, park=req.park, key=persona,
            )
        # bounded admission: shed instead of queueing unboundedly. Depth is
        # a racy-but-safe over/under-count by at most the in-flight burst;
        # the cap is an overload valve, not an exact semaphore.
        if not _prewarm:
            forced_full = self._faults.enabled and self._faults.pop(
                "engine.queue_full"
            ) is not None
            depth = self._queue.qsize() + len(self._waiting)
            if forced_full or (self.max_queue and depth >= self.max_queue):
                self.sheds += 1
                REGISTRY.counter_add("acp_engine_shed_requests_total", 1.0)
                self.flight.record("shed", rid=req.rid, depth=depth)
                req.future.set_exception(EngineOverloadedError(
                    f"admission queue full ({depth} waiting, cap "
                    f"{self.max_queue}); retry later",
                    # rough drain estimate: a slot-time per queued request,
                    # floored at 1s — advisory, clients may back off harder
                    retry_after_s=max(1.0, min(30.0, depth * 0.25)),
                ))
                self.flight.discard(req.rid)  # timeline ends at the shed
                return req.future
        self._outstanding.add(req.future)
        req.future.add_done_callback(self._outstanding.discard)
        req.future.admitted = req.admitted  # type: ignore[attr-defined]
        self._queue.put(req)
        return req.future

    def prewarm(self, constrained: bool = False) -> None:
        """Compile the jit entries real traffic will hit — a full-width
        burst of short generations with largest-bucket prompts covers the
        batched-prefill chunk sizes, the max-width decode block, and the
        narrow widths the tail decays through. With ``constrained``, a
        second burst compiles the grammar-masked variants (and builds the
        token table). Without this, the FIRST Task after startup pays
        20-40s of TPU compiles — fatal to the 500ms time-to-first-ToolCall
        target. Blocking; run from a background thread if startup latency
        matters more than first-request latency.

        Chunked-prefill engines run the legacy phases with chunking
        TEMPORARILY OFF (the phases' shape verification assumes the
        at-admission dispatch pattern; the continuation programs they
        compile are shared with the chunk loop), then one chunked phase
        warms the chunk-specific shapes."""
        ch, self.prefill_chunk = self.prefill_chunk, 0
        try:
            self._prewarm_phases(constrained)
        finally:
            self.prefill_chunk = ch
        if ch:
            self._prewarm_chunked(constrained)
            if self.megastep:
                self._prewarm_megastep(constrained)
        # from here on, a first-dispatch-of-shape is a compile REAL traffic
        # pays for: the profiler turns it into a cold_compile flight event
        # + acp_engine_cold_compiles_total (serving-time latency bug)
        self.profiler.mark_prewarmed()
        log.info("engine prewarm complete (constrained=%s)", constrained)

    def _prewarm_gap(self, phase: str, **detail) -> None:
        """A planned prewarm (bucket, batch) program shape never formed —
        its compile WILL happen at serving time. Promoted from a bare log
        line to data: a flight event plus a prewarm-coverage counter, so
        the gap is alertable instead of buried in startup logs."""
        log.warning(
            "prewarm: %s batch never formed (%s)",
            phase, " ".join(f"{k}={v}" for k, v in detail.items()),
        )
        self.flight.record("prewarm_gap", phase=phase, **detail)
        REGISTRY.counter_add(
            "acp_engine_prewarm_gaps_total", 1.0, labels={"phase": phase},
            help="prewarm coverage gaps: a planned (bucket, batch) program "
            "shape never formed during prewarm, so its compile will happen "
            "at serving time (pair with acp_engine_cold_compiles_total)",
        )

    def _prewarm_chunked(self, constrained: bool) -> None:
        """Warm the SPLIT chunk loop's shapes: multi-chunk prompts at
        every power-of-two batch size compile the KV-only chunk dispatch
        at the chunk bucket plus the final-chunk continuation buckets.
        Runs with the megastep temporarily OFF: these split programs are
        the fused path's shape-bound fallback, so they must stay warm even
        on a megastep engine (the fused shapes get their own phase,
        _prewarm_megastep)."""
        K = self.decode_block_size
        CHK = self._chunk_tokens()
        long_len = min(self.max_ctx - K - 2, CHK * 2 + max(3, CHK // 2))
        if long_len <= CHK:
            return  # every admissible prompt fits one chunk: legacy shapes cover it
        one = SamplingParams(temperature=0.0, max_tokens=1, json_only=constrained)
        ms, self.megastep = self.megastep, False
        try:
            b = 1
            while b <= min(self.prefill_batch_max, self.max_slots):
                for _attempt in range(5):
                    with self.hold_admission():
                        futs = [
                            self.submit([1] * (long_len - i), one, _prewarm=True)
                            for i in range(b)
                        ]
                    for f in futs:
                        f.result(timeout=1800)
                    if b in self._chunk_batch_sizes:
                        break
                else:
                    self._prewarm_gap("chunked", B=b)
                b *= 2
        finally:
            self.megastep = ms

    def _prewarm_megastep(self, constrained: bool) -> None:
        """Warm the fused megastep's core (bucket, batch, width) shapes:
        one long-running decoder keeps a decode phase live while b long
        prompts chunk through it, forming megastep[m{bucket}x{b}+d{W}x{K}]
        (and the final-chunk / chunks-only variants along the way) for
        every power-of-two b. Coverage is verified against the DISPATCHED
        shape set, with the standard prewarm_gap flight event + counter on
        a miss. Deliberately bounded: higher-occupancy decode widths and
        spec-verify fusions compile on demand and surface through the
        cold-compile observatory rather than paying a full width x batch x
        phase-set matrix at startup."""
        K = self.decode_block_size
        CHK = self._chunk_tokens()
        long_len = min(self.max_ctx - K - 2, CHK * 2 + max(3, CHK // 2))
        if long_len <= CHK:
            return  # nothing ever mid-prefills more than one chunk
        mid_bucket = _next_bucket(min(CHK, long_len), self.prefill_buckets)
        one = SamplingParams(temperature=0.0, max_tokens=1, json_only=constrained)

        def mid_formed(b: int) -> bool:
            want = f"m{mid_bucket}x{b}"
            return any(
                any(part.startswith(want) for part in sh[1])
                for sh in self._megastep_shapes
            )

        b = 1
        while b <= min(self.prefill_batch_max, max(1, self.max_slots - 1)):
            for _attempt in range(5):
                # a decoder long enough to outlive the chunk cycles keeps
                # the fused decode phase in every megastep of this burst
                decode_for = (
                    2 * K * (2 + b * -(-long_len // CHK))
                )
                anchor = self.submit(
                    [1] * max(1, self.prefill_buckets[0] - 1),
                    SamplingParams(temperature=0.0, max_tokens=decode_for),
                    _prewarm=True,
                )
                anchor.admitted.result(timeout=1800)
                steps0 = self.decode_steps
                for _ in range(30000):  # bounded poll, no wall-clock compare
                    if self.decode_steps != steps0:
                        break
                    time.sleep(0.002)
                with self.hold_admission():
                    futs = [
                        self.submit([1] * (long_len - i), one, _prewarm=True)
                        for i in range(b)
                    ]
                for f in futs:
                    f.result(timeout=1800)
                self.cancel(anchor)
                with contextlib.suppress(Exception):
                    anchor.result(timeout=1800)
                # verified AFTER the attempt (like _prewarm_chunked): a
                # shape forming on the final try must not record a gap
                if mid_formed(b):
                    break
            else:
                self._prewarm_gap("megastep", bucket=mid_bucket, B=b)
            b *= 2

    def _prewarm_phases(self, constrained: bool = False) -> None:
        # coverage (documented, not aspirational): per mode —
        #   (a) a full-width staggered burst at the largest bucket that
        #       leaves decode room: batched prefill at the max chunk size,
        #       then decode at max width and at EVERY narrower width bucket
        #       as the staggered max_tokens drain the low slots last;
        #   (b) a full-width burst at the LARGEST bucket, 1 token each
        #       (the long-prompt burst prefill shape);
        #   (c) one B=1 prefill per bucket, SEQUENTIAL — each awaited
        #       before the next so admission can't batch them together
        #       (the shape a lone Task hits).
        # Mid-size prefill batches (B=2/4) stay cold — rare and cheap
        # relative to covering the full bucket x batch matrix.
        K = self.decode_block_size
        widths = self.width_buckets
        max_blocks = 1 + len(widths)
        decay_bucket = self.prefill_buckets[0]
        for b in self.prefill_buckets:
            if b + max_blocks * K < self.max_ctx:
                decay_bucket = b
        if constrained:
            # build the token table BEFORE any compiles: once it exists every
            # program (constrained or not) is traced against the real table
            # shape, so the unconstrained phases below warm the same entries
            # mixed traffic will hit — not a dummy-table variant that real
            # serving immediately abandons after the first constrained request
            self._get_token_table()
        # ONE pass: with the table pre-built, constrained and unconstrained
        # requests hit the same compiled programs (json_only is runtime data,
        # not a trace shape), so a second mode pass would warm nothing new
        for json_only in [constrained]:
            # phase a: staggered decay burst (barrier: the next phase must
            # find every slot free, or its batch can't form at full width)
            with self.hold_admission():
                futs = []
                for i in range(self.max_slots):
                    # slot i outlives slot j>i: the active set decays through
                    # every width bucket
                    blocks = 1 + sum(1 for w in widths if i < w)
                    sp = SamplingParams(
                        temperature=0.0, max_tokens=blocks * K + 1, json_only=json_only
                    )
                    futs.append(
                        self.submit([1] * max(1, decay_bucket - 1), sp, _prewarm=True)
                    )
            for f in futs:
                f.result(timeout=1800)
            # phase b: full-width burst at the largest bucket
            if self.prefill_buckets[-1] != decay_bucket:
                one = SamplingParams(temperature=0.0, max_tokens=1, json_only=json_only)
                with self.hold_admission():
                    futs = [
                        self.submit([1] * (self.prefill_buckets[-1] - 1), one, _prewarm=True)
                        for _ in range(self.max_slots)
                    ]
                for f in futs:
                    f.result(timeout=1800)
            # phase c: lone-request shapes, sequential so admission can't
            # batch them together
            for b in self.prefill_buckets:
                sp = SamplingParams(temperature=0.0, max_tokens=1, json_only=json_only)
                self.submit([1] * max(1, b - 1), sp, _prewarm=True).result(timeout=1800)
            # phase c2: remaining (bucket, batch) plain-prefill programs —
            # staggered arrivals (the operator's reconcile cadence) land
            # mid-size chunks (B=2/4) that the full-width bursts above never
            # form; each (bucket, B) is its own compiled program. Verified
            # against the dispatch record like phases d/e.
            one = SamplingParams(temperature=0.0, max_tokens=1, json_only=json_only)
            Bsz = 2
            while Bsz <= min(self.prefill_batch_max, self.max_slots):
                for idx, b in enumerate(self.prefill_buckets):
                    prev = self.prefill_buckets[idx - 1] if idx else 0
                    if (b, Bsz) in self._full_batch_shapes:
                        continue  # covered by an earlier phase/run
                    if b - Bsz <= prev:
                        continue  # bucket too narrow for Bsz distinct lengths
                    for _attempt in range(5):
                        with self.hold_admission():
                            futs = [
                                self.submit([1] * (b - 1 - i), one, _prewarm=True)
                                for i in range(Bsz)
                            ]
                        for f in futs:
                            f.result(timeout=1800)
                        if (b, Bsz) in self._full_batch_shapes:
                            break
                    else:
                        self._prewarm_gap("plain", bucket=b, B=Bsz)
                Bsz *= 2
            # phase d: the prefix-cache CONTINUATION program: a seed request,
            # then hitting bursts at every power-of-two batch size up to
            # min(prefill_batch_max, max_slots) (distinct tails so a burst
            # forms one conts chunk). These must go through the real cache
            # path, so they are NOT _prewarm requests; their dummy entries
            # (token-1/2 keys) and their exact hit/miss deltas are removed
            # right after.
            if self._prefix_enabled:
                # phase-d requests ride the REAL submit path (non-
                # _prewarm, to exercise the cache) — lift the admission
                # cap so a small max_queue can't shed prewarm's own burst.
                # Dedup is paused too: its leader scan would intercept the
                # same-prefix burst before the cache could, and the
                # continuation batch shapes this phase exists to compile
                # would never form.
                cap, self.max_queue = self.max_queue, 0
                dd, self.prefix_dedup = self.prefix_dedup, False
                try:
                    seed_len = self.prefill_buckets[0] + 1
                    one = SamplingParams(temperature=0.0, max_tokens=1, json_only=json_only)
                    self.submit([1] * seed_len, one).result(timeout=1800)
                    d_hits = 0
                    b = 1
                    while b <= min(self.prefill_batch_max, self.max_slots):
                        # burst formation depends on queue-drain timing: verify
                        # the batch size actually DISPATCHED and retry, rather
                        # than assuming the b submits landed in one group
                        for _attempt in range(5):
                            with self.hold_admission():
                                futs = [
                                    self.submit([1] * seed_len + [2] * (8 + i), one)
                                    for i in range(b)
                                ]
                            for f in futs:
                                f.result(timeout=1800)
                            d_hits += b
                            if b in self._cont_batch_sizes:
                                break
                        else:
                            self._prewarm_gap("continuation", B=b)
                        b *= 2
                    with self._prefix_lock:
                        for key in [
                            k for k in self._prefix_cache if set(k) <= {1, 2}
                        ]:
                            old = self._prefix_cache.pop(key)
                            if "pages" in old:
                                self._allocator.free(old["pages"])
                        self._prefix_hits = max(0, self._prefix_hits - d_hits)
                        self._prefix_misses = max(0, self._prefix_misses - 1)
                finally:
                    self.max_queue = cap
                    self.prefix_dedup = dd
            # phase e: chunked-prefill SPILL shapes (configs whose largest
            # bucket is below max_ctx): long prompts at every power-of-two
            # batch size, with the same verified-dispatch retry as phase d
            CH = self.prefill_buckets[-1]
            if CH < self.max_ctx:
                long_len = min(self.max_ctx - K - 2, CH * 2)
                one = SamplingParams(temperature=0.0, max_tokens=1, json_only=json_only)
                b = 1
                while b <= min(self.prefill_batch_max, self.max_slots):
                    for _attempt in range(5):
                        with self.hold_admission():
                            futs = [
                                self.submit([1] * (long_len + i), one, _prewarm=True)
                                for i in range(b)
                            ]
                        for f in futs:
                            f.result(timeout=1800)
                        if b in self._spill_batch_sizes:
                            break
                    else:
                        self._prewarm_gap("spill", B=b)
                    b *= 2

    def cancel(self, future: Future) -> None:
        """Abort the request behind a Future returned by :meth:`submit`.
        Thread-safe and best-effort: a waiting request is failed immediately
        on the engine thread; an active slot is freed (KV pages released) at
        the next decode iteration with finish_reason "cancelled"."""
        rid = getattr(future, "rid", None)
        # accept already-CANCELLED futures: asyncio.wait_for(wrap_future(f))
        # cancels the underlying concurrent Future before the caller's
        # except-block runs, but the slot is still decoding
        if rid is not None and (not future.done() or future.cancelled()):
            self._cancelled.add(rid)

    def generate(self, prompt: str | list[int], sampling: Optional[SamplingParams] = None) -> GenerationResult:
        """Synchronous helper (tests/benchmarks). Requires a started engine."""
        return self.submit(prompt, sampling).result(timeout=600)

    def stats(self) -> dict:  # acp: cross-thread
        """Point-in-time status snapshot (served at /v1/engine). Reads of
        engine-thread state are racy-but-safe: ints/lens only (enforced by
        the acplint thread-ownership pass against the mirror registry)."""
        out = {
            "model": {
                "dim": self.config.dim,
                "layers": self.config.n_layers,
                "vocab": self.config.vocab_size,
                "quantize": self.quantize,
                "quantize_kv": self.quantize_kv,
                "weight_bytes": self.weight_bytes,
            },
            "kv_layout": self.kv_layout,
            "max_slots": self.max_slots,
            "max_ctx": self.max_ctx,
            "active_slots": self._n_active(),
            "parked_slots": self._parked_count,
            "prefilling_slots": self._prefilling_count,
            "waiting": len(self._waiting),
            "max_queue": self.max_queue,
            "preemptions": self.preemptions,
            "preempted_waiting": self._preempted_waiting(),
            "decode_block_size": self.decode_block_size,
            "decode_steps": self.decode_steps,
            "tokens_generated": self.tokens_generated,
            # gray-failure signals (fleet/health.py samples these): cycle
            # cadence EWMA, watchdog stall count, admission sheds
            "cycle_s": round(self._cycle_s, 6),
            "stalls": self.stalls,
            "sheds": self.sheds,
            # degradation ladder posture (engine/brownout.py)
            "brownout": {
                "enabled": self.brownout_enabled,
                "level": self._brownout_level,
                "steps_down": (
                    self._brownout.steps_down if self._brownout is not None else 0
                ),
                "steps_up": (
                    self._brownout.steps_up if self._brownout is not None else 0
                ),
            },
            # decode efficiency: tokens committed per model step. Without
            # speculation this is <= 1 (finished lanes pad blocks); with it,
            # each verify dispatch counts ONE step however many tokens land,
            # so > 1 means speculation is paying.
            "tokens_per_decode_step": (
                round(self.tokens_generated / self.decode_steps, 4)
                if self.decode_steps else 0.0
            ),
            "tool_overlap": {
                "early_calls": self.tool_calls_early,
                "overlap_saved_s": round(self.tool_overlap_saved_s, 4),
                "parks": self.parks,
                "park_adoptions": self.park_adoptions,
                "park_releases": self.park_releases,
                "park_max_s": self.park_max_s,
            },
            # unified token-budget scheduler (chunked prefill); utilization
            # is tokens dispatched / per-cycle budget — persistently low
            # means the budget is oversized for the traffic, ~1.0 with
            # waiting chunks means prefill is throttled by it
            "scheduler": {
                "chunked_prefill": self.prefill_chunk > 0,
                "prefill_chunk": self.prefill_chunk,
                "token_budget": self.token_budget,  # 0 = auto-sized
                "prefill_chunks_total": self.prefill_chunks,
                "hol_wait_seconds": round(self.hol_wait_s, 4),
                "budget_utilization_last": (
                    round(min(1.0, self._budget_last[1] / self._budget_last[0]), 4)
                    if self._budget_last[0] else 0.0
                ),
                "budget_utilization_avg": (
                    round(min(1.0, self._budget_spent_total / self._budget_total), 4)
                    if self._budget_total else 0.0
                ),
                # fused megastep dispatch: one compiled program per busy
                # cycle instead of 1 + #chunk-batches + #final-batches
                "megastep": {
                    "enabled": self.megastep,
                    "dispatches": self.megastep_dispatches,
                    "shapes": len(self._megastep_shapes),
                    "max_programs": self.megastep_max_programs,
                    "fallbacks": self.megastep_fallbacks,
                },
                # admission-time chunk-rate planner + autopilot
                "planner": {
                    "enabled": self.rate_planner,
                    "quota_projections": self.quota_projections,
                    "quota_reprojections": self.quota_reprojections,
                    "autopilot": self.autopilot_enabled,
                    "autopilot_adjustments": (
                        self._autopilot.adjustments
                        if self._autopilot is not None else 0
                    ),
                },
            },
            "spec": {
                "enabled": self.spec_len > 0,
                "spec_len": self.spec_len,
                "ngram": self.spec_ngram,
                "proposed": self.spec_proposed,
                "accepted": self.spec_accepted,
                "acceptance_rate": (
                    round(self.spec_accepted / self.spec_proposed, 4)
                    if self.spec_proposed else 0.0
                ),
                "verify_dispatches": self.spec_dispatches,
            },
            # KV memory tiers: host-RAM offload pool occupancy + cross-
            # request shared-prefix dedup payoff (mirror ints, engine-side
            # refreshed by _publish_memory_state after every cycle)
            "memory": {
                "host_kv": {
                    "enabled": self.host_kv_bytes > 0,
                    "max_bytes": self.host_kv_bytes,
                    "used_bytes": self._host_kv_used,
                    "entries": self._host_kv_entries,
                    "swap_outs": self.kv_swap_outs,
                    "swap_ins": self.kv_swap_ins,
                    "injects": self.kv_injects,
                },
                "prefix_dedup": {
                    "enabled": self.prefix_dedup and self.kv_layout == "paged",
                    "shares": self.prefix_shares,
                    "shared_pages": self._prefix_shared_pages,
                },
                # int8 KV cache (quantize_kv): at a fixed HBM budget the
                # pool holds ~2x the tokens; compounds with the host tier
                # and dedup above (both carry the quantized bytes)
                "quantized_kv": {
                    "enabled": self.quantize_kv,
                    "pages": (
                        self._allocator.allocated_count  # acp-lint: disable=thread-ownership
                        if self.quantize_kv and self.kv_layout == "paged"
                        else 0
                    ),
                },
            },
            "mesh": {
                name: int(size)
                for name, size in zip(self.mesh.axis_names, self.mesh.devices.shape)
            },
            # flight recorder occupancy (the recorder's own methods take
            # its lock; self.flight is a public attribute, never mutated)
            "flight": self.flight.stats(),
            # compute efficiency observatory: per-program dispatch stats,
            # cold-compile tracking, goodput/waste ledger (the profiler's
            # stats() is its declared cross-thread read surface)
            "perf": self.profiler.stats(),
        }
        if self.kv_layout == "paged":
            out["kv_pages"] = {
                "total": self.num_pages - 1,
                # free_count is len() of the allocator's free list — the
                # same atomic-len contract as len(self._waiting) below, just
                # behind a property the AST pass can't see through
                "free": self._allocator.free_count,  # acp-lint: disable=thread-ownership
                "page_size": self.page_size,
                "table_uploads": self.table_uploads,
            }
        if self._prefix_enabled:
            with self._prefix_lock:
                out["prefix_cache"] = {
                    "entries": len(self._prefix_cache),
                    "capacity": self._prefix_cache_entries,
                    "hits": self._prefix_hits,
                    "misses": self._prefix_misses,
                    "cached_tokens": self._cached_tokens_locked(),
                }
        return out

    def _preempted_waiting(self) -> int:  # acp: cross-thread
        """Requeued-after-preemption count; tolerant of cross-thread reads
        (the engine thread mutates the deque while stats() iterates).
        Preempted requests are only ever requeued at the FRONT and fresh
        arrivals only append at the back, so they form a contiguous prefix
        — the scan stops at the first non-preempted request instead of
        walking a potentially deep backlog every decode block."""
        n = 0
        try:
            # deque iteration raises (caught below) instead of tearing —
            # the one sanctioned non-len cross-thread read in the engine
            for r in self._waiting:  # acp-lint: disable=thread-ownership
                if not r.preempt_count:
                    break
                n += 1
        except RuntimeError:  # deque mutated during iteration: racy read
            pass
        return n

    # -- engine loop -----------------------------------------------------

    def _run(self) -> None:  # acp: idle-loop
        try:
            while not self._stopping:
                admitted = self._admit(block=not self._has_work())
                if self._stopping:
                    break
                # stall-watchdog window: everything between here and the
                # post-dispatch check counts as ONE cycle's wall time —
                # including fault-injected throttles (engine.slow_cycle),
                # which is exactly the wedge the watchdog exists to see
                t_cycle = time.monotonic()
                # after _admit, not before: the loop parks in _admit while
                # idle, so a crash armed then would otherwise fire only
                # AFTER the next request completed a full loop iteration —
                # here it fires with that request admitted but unresolved,
                # which is the recovery path worth testing
                if self._faults.enabled and self._faults.pop("engine.crash") is not None:
                    raise RuntimeError("fault injection: engine crash")
                if (
                    self._faults.enabled
                    and self.fleet_replica_id is not None
                    and self._faults.pop(
                        "fleet.replica_crash", steps=self.decode_steps,
                        match={"replica": self.fleet_replica_id},
                    ) is not None
                ):
                    # pool failover drill: only the NAMED replica dies (the
                    # match filter keeps sibling engines in the same process
                    # alive); after_steps gates it mid-decode
                    raise RuntimeError("fault injection: fleet replica crash")
                if self._faults.enabled and (admitted or self._has_work()):
                    # throttle drill: stretch scheduler cycles so wall-clock
                    # races (deadlines, mid-flight cancels) land while
                    # requests are genuinely queued/decoding — a tiny model
                    # on fast hardware otherwise outruns any realistic
                    # timer. Timing-only: sampled tokens are untouched.
                    # BUSY cycles only: _admit's idle park wakes on a short
                    # timeout, and letting those empty iterations pop would
                    # silently drain the times= budget before work arrives.
                    # match on the fleet identity (when registered) so a
                    # spec armed with replica="rN" throttles exactly the
                    # named replica — the gray-failure drill — while an
                    # unscoped spec keeps firing on any engine
                    slow = self._faults.pop(
                        "engine.slow_cycle",
                        match={"replica": self.fleet_replica_id},
                    )
                    if slow is not None:
                        time.sleep(float(slow.get("delay_s", 0.01)))
                self._sweep_parked()
                if not self._has_work():
                    if not admitted:
                        # park sweeps / admission pressure can free shared
                        # pages or swap KV without a dispatch following —
                        # keep the memory mirrors fresh on the idle path too
                        self._publish_memory_state()
                        continue
                self._dispatch_once()
                self._stall_check(time.monotonic() - t_cycle)
                # memory-tier mirrors/gauges refresh BEFORE the armed audit
                # below, so mirror-vs-truth checks see post-cycle state
                self._publish_memory_state()
                # goodput/waste ledger counters + ratio gauge (delta-based;
                # the scrape path refreshes them too via stats())
                self.profiler.publish()
                if self._autopilot is not None:
                    self._autopilot_tick()
                if self._brownout is not None:
                    self._brownout_tick()
                if self.check_invariants:
                    if self._faults.enabled and self._faults.pop(
                        "engine.invariant_break"
                    ) is not None:
                        # deterministic mirror corruption: prove the armed
                        # checker trips end to end (see faults.py)
                        self._parked_count += 1
                    from .invariants import check_engine_invariants

                    check_engine_invariants(self)
        except Exception as e:  # an engine crash must not hang callers
            log.exception("engine loop crashed")
            # flight-record the crash and snapshot the black box BEFORE any
            # state is torn down — the dump must show the engine as the
            # crash found it (last-N events + stats + allocator audit);
            # ACP_FLIGHT_DUMP_DIR unset (the default) skips the file
            self.flight.record("crash", error=repr(e))
            self.flight.dump_crash(self, e)
            self._slots.clear()
            self._parked_count = 0
            self._prefilling_count = 0
            self._publish_park_gauge()
            self._stopping = True
            self._crashed = True  # restartable (see ensure_running)
            REGISTRY.counter_add("acp_engine_crashes_total", 1.0)
            for fut in list(self._outstanding):
                if not fut.done():
                    fut.set_exception(RuntimeError(f"engine crashed: {e}"))
        # drain: fail any queued/waiting requests
        while True:
            try:
                req = self._queue.get_nowait()
            except queue.Empty:
                break
            if req is not None:
                self._waiting.append(req)
        while self._waiting:
            fut = self._waiting.popleft().future
            if not fut.done():  # crash handler may have failed it already
                fut.set_exception(RuntimeError("engine stopped"))
        for slot in list(self._slots):
            self._finish(slot, "stop")
        # drop whatever live timelines the drain didn't retire (the global
        # window keeps the raw events — including for the crash dump above)
        self.flight.discard_live()

    @contextlib.contextmanager
    def hold_admission(self):
        """Deterministic batch formation: while held, submitted requests
        accumulate in the waiting deque (the engine keeps decoding active
        slots) and on release ONE admission group forms with the whole
        batch. Prewarm uses this so its (bucket, B) / continuation /
        spill batch shapes form on the first attempt instead of racing the
        engine loop's drain timing — a missed shape there is a 20-40s cold
        compile in the middle of real serving."""
        with self._admission_lock:
            self._admission_held += 1
        try:
            yield
        finally:
            with self._admission_lock:
                self._admission_held -= 1

    def _admit(self, block: bool) -> bool:
        """Move queued requests into free slots (prefill), strictly FIFO.
        Returns True if anything was admitted.

        Multi-host lockstep: the request stream is the ONLY nondeterministic
        input to admission, so the leader broadcasts each iteration's drained
        requests + cancel snapshot as a frame and followers replay it — every
        process then runs the identical pure admission logic and joins the
        identical global dispatches (see engine/coordination.py)."""
        may_block = block and not self._waiting and not self._has_work()
        if self._coord_follower:
            try:
                frame = self._coordination.recv()
            except (ConnectionError, OSError) as e:
                if self._stopping:  # local stop() closed the channel
                    return False
                raise RuntimeError(f"serving coordination channel lost: {e}") from e
            if frame["stop"]:
                self._stopping = True
                return False
            from .coordination import deserialize_request

            for doc in frame["reqs"]:
                self._waiting.append(deserialize_request(doc))
            self._applied_cancels.update(frame["cancels"])
            held = bool(frame.get("hold"))
        else:
            # drain the cross-thread queue into the ordered waiting deque
            drained: list[_Request] = []
            saw_stop = False
            while True:
                try:
                    req = self._queue.get(timeout=0.05) if may_block else self._queue.get_nowait()
                except queue.Empty:
                    break
                may_block = False
                if req is None:
                    saw_stop = True
                    break
                drained.append(req)
            # the hold state is read ONCE and drives both the frame and the
            # local decision — a live re-read below could release between
            # publish and fill, desynchronizing ranks
            held = bool(self._admission_held)
            if self._coordination is not None:
                # leader: only cancels whose requests are already part of
                # the replicated stream may be published — a cancel racing
                # its own still-in-transit request would be pruned by
                # followers before the request arrives, then admitted there
                # but cancelled here. Unpublishable cancels wait in
                # _cancelled for a later frame; truly stale rids (request
                # already finished) are pruned against the in-transit queue.
                # Snapshot FIRST: cancel() adds rids from other threads with
                # no lock, so every prune below must remove only rids this
                # snapshot examined against liveness views taken AFTER it.
                # The previous live-set intersection dropped a cancel that
                # landed after the snapshots for a request submitted after
                # the transit peek — that request then decoded to max_tokens
                # uncancellable.
                # Expire BEFORE the snapshot: an expired-while-queued rid
                # then rides THIS frame's cancel list and is dropped from
                # every rank's waiting deque before _fill_slots — otherwise
                # the dead request would be prefilled once while its cancel
                # waited for the next frame.
                self._expire_deadlines()
                snapshot = set(self._cancelled)
                published_live = {r.rid for r in self._waiting}
                published_live.update(
                    sl.request.rid for sl in self._slots.values()
                )
                published_live.update(r.rid for r in drained)
                pending = snapshot & published_live
                with self._queue.mutex:
                    transit = {
                        r.rid for r in self._queue.queue if r is not None
                    }
                # pending publishes now; snapshot rids live nowhere are
                # truly stale; anything cancel() added since the snapshot
                # stays for the next iteration's examination
                self._cancelled -= pending
                self._cancelled -= snapshot - (transit | published_live)
                # publish BEFORE applying, so a crash between the two can
                # only lose work symmetrically (followers time out)
                self._coordination.publish(
                    drained, sorted(pending), stop=saw_stop, hold=held
                )
                self._applied_cancels.update(pending)
            if saw_stop:
                self._stopping = True
                # hand the drained-but-never-admitted requests to the
                # shutdown drain so their futures fail instead of hanging
                self._waiting.extend(drained)
                return False
            self._waiting.extend(drained)

        if self._applied_cancels and self._waiting:
            kept = type(self._waiting)()
            while self._waiting:
                r = self._waiting.popleft()
                if r.rid in self._applied_cancels:
                    self._applied_cancels.discard(r.rid)
                    r.future.cancel()
                    if not r.prewarm:
                        self.flight.record("cancel", rid=r.rid, where="queued")
                        self.flight.discard(r.rid)
                else:
                    kept.append(r)
            self._waiting = kept
        if self._applied_cancels:
            # purge rids that raced _finish (request already completed): a
            # stale rid could collide with a future request's rid. A rid is
            # live if its request is waiting or active — plus, single-host
            # only, still in transit in the cross-thread queue (peeked under
            # the queue mutex; without this a submit-then-cancel racing the
            # drain loses the cancel). Under coordination in-transit rids
            # are never in _applied_cancels, so the liveness rule is
            # identical on every rank.
            # snapshot-then-subtract, NOT a live intersection: single-host
            # _applied_cancels IS _cancelled, which cancel() mutates from
            # other threads — an intersection drops a cancel added after the
            # liveness views for a request still in transit
            snapshot = set(self._applied_cancels)
            live = {r.rid for r in self._waiting}
            live.update(sl.request.rid for sl in self._slots.values())
            if self._coordination is None:
                with self._queue.mutex:
                    live.update(r.rid for r in self._queue.queue if r is not None)
            self._applied_cancels -= snapshot - live

        self._expire_deadlines()
        if held:
            if not self._has_work():
                # idle hold: don't busy-spin against the submitting thread
                time.sleep(0.002)
            return False
        return self._fill_slots()

    def _expire_deadlines(self) -> None:  # acp: leader-local
        """Fail queued requests whose deadline passed — fast, before any
        prefill is spent on them. Single-host: fail in place. Coordinated
        leader: route through the replicated cancel stream (wall-clock
        decisions must not fork lockstep); followers never expire locally."""
        if self._coord_follower:
            return
        expired = [
            r for r in self._waiting
            if r.deadline is not None
            and time.monotonic() > r.deadline
            and not r.future.done()
        ]
        if not expired:
            return
        if self._coordination is not None:
            for r in expired:
                # the future lives only on the leader (followers reject
                # local submissions): resolving it here is host-local and
                # cannot fork lockstep, while the rid rides the replicated
                # cancel stream so every rank drops the request from its
                # waiting deque in the same frame. The stream's later
                # future.cancel() is a no-op on the already-failed future —
                # without this the client would see a spurious
                # CancelledError instead of the deadline 504.
                r.future.set_exception(DeadlineExceededError(
                    self._expiry_message(r)
                ))
                REGISTRY.counter_add("acp_engine_deadline_expired_total", 1.0)
                self._record_expire(r, "queued")
                self._cancelled.add(r.rid)  # rides the next published frame
            return
        gone = {id(r) for r in expired}
        kept = type(self._waiting)(r for r in self._waiting if id(r) not in gone)
        self._waiting = kept
        for r in expired:
            r.future.set_exception(DeadlineExceededError(self._expiry_message(r)))
            REGISTRY.counter_add("acp_engine_deadline_expired_total", 1.0)
            self._record_expire(r, "queued")

    def _record_expire(self, req: _Request, where: str) -> None:
        """Flight-record a deadline expiry and retire the timeline (the
        request is terminal; its phases end at the expiry)."""
        if req.prewarm:
            return
        self.flight.record("expire", rid=req.rid, where=where)
        self.flight.discard(req.rid)

    @staticmethod
    def _expiry_message(req: _Request) -> str:
        """Distinguish never-admitted expiry from expiry while requeued
        after a preemption — the latter DID spend compute and stream
        tokens, and conflating them misleads capacity debugging."""
        return (
            "deadline expired while queued (never admitted)"
            if req.first_token_at == 0.0
            else "deadline expired while requeued after preemption"
        )

    def _fill_slots(self) -> bool:
        """Admit from the waiting deque into free slots (the prefill side
        of _admit, split out so the coordinated multi-host loop can replay
        broadcast admissions without touching the local submit queue)."""
        self._drain_kv_inject()
        admitted = False
        while self._waiting and (self._free or self._has_parked()):
            group = self._collect_group()
            if not group:
                break  # head request can't fit (KV pages); FIFO, wait
            admitted = True
            for item in group:
                # starts the client's generation clock; a caller that gave
                # up (timeout/cancel) may have cancelled the future already
                with contextlib.suppress(InvalidStateError):
                    item[0].admitted.set_result(True)
            # per item: resolve the prefix-cache start (match + page
            # assembly already happened in _collect_group), then spill any
            # overlong remainder through intermediate continuation chunks
            # (chunked prefill — both layouts)
            enriched: list[list] = []  # [item, start, swap_entry, share_of]
            for item in group:
                req, slot, _pages, match = item
                start = 0
                swap = None
                share = None
                if match is not None and match[1].get("in_slot"):
                    # adopted parked slot: the prompt KV is already resident
                    # in THIS slot — no copy, just a suffix start offset
                    start = match[1]["cut"]
                elif match is not None and match[1].get("swap") is not None:
                    # host-tier restore: rows swap back in chunk by chunk
                    # through the budget loop (start stays 0 — prefill_pos
                    # advances as restored rows land)
                    swap = match[1]["swap"]
                elif match is not None and match[1].get("share_of") is not None:
                    # dedup follower: rows [0, cut) are the leader's
                    # refcount-shared pages — nothing to copy, but the
                    # model prefill may have to WAIT for the leader to
                    # write them (mid-prefill leader), so the follower is
                    # admitted through the prefilling path in every mode
                    start = match[1]["cut"]
                    share = (*match[1]["share_of"], start)
                elif match is not None:
                    if self.kv_layout == "slot":
                        self._copy_prefix_into_slot(slot, match[1])
                    # paged: the shared prefix pages are already in the
                    # block table; nothing to copy
                    start = match[1]["cut"]
                    self._prefix_hits += 1
                    REGISTRY.counter_add("acp_engine_prefix_cache_hit_requests", 1.0)
                elif self._prefix_enabled and not req.truncated:
                    self._prefix_misses += 1
                    REGISTRY.counter_add("acp_engine_prefix_cache_miss_requests", 1.0)
                if not req.prewarm:
                    # admit = the reservation decision: slot id (+ pages in
                    # paged mode) taken, prefix-cache start resolved. In
                    # chunked mode no model compute has run yet (reserve)
                    self.flight.record(
                        "admit", rid=req.rid, slot=slot,
                        start=start, pages=len(_pages) if _pages else 0,
                        resumed=req.preempt_count > 0,
                        adopted=bool(match is not None and match[1].get("in_slot")),
                        chunked=bool(self.prefill_chunk),
                        swapped=swap is not None, shared=share is not None,
                    )
                enriched.append([item, start, swap, share])
            if self.kv_layout == "paged":
                # block tables must exist before spill chunks reference them
                for item in group:
                    _req, slot, pages, _m = item
                    assert pages is not None
                    self._slot_pages[slot] = pages
                    self._block_tables[slot, :] = TRASH_PAGE
                    self._block_tables[slot, : len(pages)] = pages
            if self.prefill_chunk:
                # chunked mode: admission only RESERVES (slot id + pages +
                # prefix-cache start); all prefill compute happens one chunk
                # per dispatch cycle in _prefill_chunks, interleaved with
                # decode — a long prompt never stalls decoding slots for its
                # whole prefill
                for item, start, swap, share in enriched:
                    req, slot, _pages, _m = item
                    # re-admission edges REPROJECT the chunk-rate plan:
                    # preempt->resume and park->adopt both land here
                    reason = (
                        "resume" if req.preempt_count
                        else "adopt" if _m is not None and _m[1].get("in_slot")
                        else "admit"
                    )
                    self._begin_chunked_prefill(
                        req, slot, start, swap=swap, share_of=share,
                        reason=reason,
                    )
                continue
            # host restores and dedup followers go through the prefilling
            # path even with chunking off: a restore is budget-metered and
            # a follower may wait on its leader — both drain through the
            # chunk loop (keyed on _prefilling_count, not the knob)
            deferred = [e for e in enriched if e[2] is not None or e[3] is not None]
            direct = [e for e in enriched if e[2] is None and e[3] is None]
            for item, start, swap, share in deferred:
                req, slot, _pages, _m = item
                self._begin_chunked_prefill(req, slot, start, swap=swap, share_of=share)
            with self._hol_clock():
                self._spill_long_chunks(direct)
                plain = [e for e in direct if e[1] == 0]  # cheaper causal program
                conts = [e for e in direct if e[1] > 0]  # suffix continuation
                for chunk in _pow2_chunks(plain, self.prefill_batch_max):
                    self._prefill_group([e[0] for e in chunk])
                for chunk in _pow2_chunks(conts, self.prefill_batch_max):
                    self._prefill_group(
                        [e[0] for e in chunk],
                        starts_np=np.asarray([e[1] for e in chunk], dtype=np.int32),
                    )
        return admitted

    def _spill_long_chunks(self, enriched: list[list]) -> None:  # acp: megastep-seam
        # acp: dispatch-lanes toks,starts,slots,page_ids
        """Chunked prefill, batched across the admission group: round-robin
        one largest-bucket chunk per long request per dispatch (KV writes
        only; the sampled token is discarded) until every remainder fits one
        bucket. Mutates each item's start offset in place."""
        CH = self.prefill_buckets[-1]
        while True:
            need = [
                e for e in enriched
                if len(self._full_row(e[0][0])) - e[1] > CH
            ]
            if not need:
                return
            for batch in _pow2_chunks(need, self.prefill_batch_max):
                B = len(batch)
                self._spill_batch_sizes.add(B)
                toks = np.zeros((B, CH), dtype=np.int32)
                starts = np.zeros(B, dtype=np.int32)
                slots = np.zeros(B, dtype=np.int32)
                for i, e in enumerate(batch):
                    (req, slot, _, _m), start = e[0], e[1]
                    toks[i] = self._full_row(req)[start : start + CH]
                    starts[i] = start
                    slots[i] = slot
                self._rng, step_rng = jax.random.split(self._rng)
                tail = (
                    step_rng,
                    self._put(np.zeros(B, dtype=np.float32)),  # temps (unused sample)
                    self._put(np.zeros(B, dtype=np.int32)),
                    self._put(np.ones(B, dtype=np.float32)),
                    self._dummy_table,
                    self._put(np.zeros(B, dtype=np.int32)),
                    self._put(np.zeros(B, dtype=bool)),  # unconstrained
                    self._dummy_min_close,
                    self._put(np.ones(B, dtype=np.int32)),
                )
                prof_t0 = self.profiler.start()
                if self.kv_layout == "paged":
                    P = self.page_size
                    page_ids = np.zeros((B, CH // P), dtype=np.int32)
                    for i, e in enumerate(batch):
                        slot, start = e[0][1], e[1]
                        page_ids[i] = self._slot_pages[slot][start // P : (start + CH) // P]
                    block_tables = self._put(
                        self._block_tables[[it[0][1] for it in batch]]
                    )
                    self.cache, _tok, _state = self._jit_prefill_paged_continue(
                        self.params,
                        self.cache,
                        self._put(toks),
                        self._put(np.full(B, CH, dtype=np.int32)),
                        self._put(starts),
                        self._put(page_ids),
                        block_tables,
                        *tail,
                    )
                else:
                    self.cache, _tok, _state = self._jit_prefill_continue(
                        self.params,
                        self.cache,
                        self._put(toks),
                        self._put(np.full(B, CH, dtype=np.int32)),
                        self._put(starts),
                        self._put(slots),
                        *tail,
                    )
                if self.profiler.enabled:
                    # spill rounds run full CH-token rows: no bucket padding
                    self.profiler.record(
                        f"spill[{self.kv_layout},{CH}x{B}]", prof_t0,
                        out=_tok, real_tokens=B * CH, real_slots=B,
                    )
                    pre = sum(CH for e in batch if e[0][0].prewarm)
                    self.profiler.account(goodput=B * CH - pre, prewarm=pre)
                for e in batch:
                    e[1] += CH

    # -- chunked prefill + unified token-budget scheduler -----------------

    @contextlib.contextmanager
    def _hol_clock(self):
        """Attribute prefill wall time to head-of-line decode stall: while
        any slot is actively DECODING, every second spent inside a prefill
        dispatch is a second those slots' tokens arrive late. Wraps the
        legacy at-admission prefill (the monolithic stall chunking removes)
        and the chunked path's per-cycle chunk dispatches (the residual
        stall that remains), so the same metric compares both modes."""
        stalled = self._n_active() > 0
        t0 = time.monotonic()
        try:
            yield
        finally:
            if stalled:
                dt = time.monotonic() - t0
                self.hol_wait_s += dt
                REGISTRY.counter_add(
                    "acp_engine_hol_wait_seconds", dt,
                    help="seconds decoding slots were stalled behind "
                    "prefill dispatches (head-of-line blocking)",
                )

    def _chunk_tokens(self) -> int:
        """Effective chunk size: clamped to the largest prefill bucket
        (each chunk is one continuation dispatch at a compiled bucket) and,
        in paged mode, rounded UP to a page multiple — non-final chunks
        commit whole pages, so every chunk boundary must be page-aligned.
        prefill_chunk == 0 here means the knob was toggled off while slots
        were still mid-prefill (_dispatch_once drains them through the
        chunk loop regardless): drain at the largest bucket — collapsing
        to 1-token chunks would break paged page alignment and crawl."""
        ch = min(
            self.prefill_chunk or self.prefill_buckets[-1],
            self.prefill_buckets[-1],
        )
        if self.kv_layout == "paged":
            ch = -(-ch // self.page_size) * self.page_size
        return max(1, ch)

    def _begin_chunked_prefill(
        self,
        req: _Request,
        slot: int,
        start: int,
        swap: Optional[object] = None,
        share_of: Optional[tuple] = None,
        reason: str = "admit",
    ) -> None:
        """Admit a request as a PREFILLING slot: the slot id and (paged) KV
        pages are reserved and the prefix-cache start resolved, but no model
        compute has run — the unified scheduler advances it chunk by chunk.
        ``start`` rows of KV are already valid (prefix-cache copy, shared
        pages, or an adopted parked slot's resident prompt). ``swap`` is a
        host-tier entry whose rows restore through the budget loop before
        any model chunk; ``share_of`` marks a dedup follower that may wait
        on its leader's prefill (see _prefill_chunks)."""
        self._admit_seq += 1
        sl = _Slot(
            request=req,
            prompt_len=len(req.prompt),
            prefix_len=len(req.sampling.forced_prefix),
            admit_seq=self._admit_seq,
            prefilling=True,
            prefill_pos=start,
        )
        sl.prefill_row = self._full_row(req)
        sl.swap_entry = swap
        sl.share_of = share_of
        self._project_quota(slot, sl, reason)
        self._slots[slot] = sl
        self._prefilling_count += 1
        self._seq_lens[slot] = start
        self._last_tokens[slot] = 0
        self._state_dirty = True  # the lane must upload as inactive

    def _project_quota(self, slot: int, sl: _Slot, reason: str) -> None:  # acp: leader-local
        """Admission-time chunk-rate plan (engine/planner.py): convert the
        request's deadline into a per-cycle chunk quota so the prefill
        finishes by arithmetic, not EDF luck. Projected at admission and
        REPROJECTED at the re-admission edge of every displacement event —
        preempt→resume and park→adopt both re-enter here, so a displaced
        request's plan always reflects its remaining tokens and remaining
        time. Leader-local: deadlines are host wall clock, so followers
        (and every rank under coordination — the EDF fallback rule) keep
        quota 1."""
        if self._coord_follower:
            return
        sl.chunk_quota = 1
        if (
            not self.rate_planner
            or self._coordination is not None
            or sl.request.deadline is None
        ):
            return
        from .planner import project_quota

        tokens_left = max(0, len(sl.prefill_row or []) - sl.prefill_pos)
        seconds_left = sl.request.deadline - time.monotonic()
        sl.chunk_quota = project_quota(
            tokens_left,
            self._chunk_tokens(),
            seconds_left,
            self._cycle_clock.cycle_s or 0.05,
            max_quota=self.planner_max_quota,
        )
        self.quota_projections += 1
        if reason != "admit":
            self.quota_reprojections += 1
            REGISTRY.counter_add(
                "acp_engine_quota_reprojections_total", 1.0,
                help="chunk-rate plans recomputed at a re-admission edge "
                "(preempt-resume / park-adopt) — each is a displaced "
                "request whose remaining-time arithmetic changed",
            )
        if not sl.request.prewarm:
            self.flight.record(
                "quota", rid=sl.request.rid, slot=slot,
                quota=sl.chunk_quota, tokens_left=tokens_left,
                seconds_left=round(max(0.0, seconds_left), 4),
                reason=reason,
            )

    def _autopilot_tick(self) -> None:
        """Scheduler autopilot (engine/planner.py): on interval
        boundaries, let the observed phase attribution steer the
        scheduling knobs one bounded step. The flight recorder graduates
        from diagnostic to controller; every adjustment is itself a
        flight event, so the control loop stays inspectable."""
        ap = self._autopilot
        if ap is None or not ap.due():
            return
        from ..observability.flight import phase_summaries

        phases = {k: v.get("p99", 0.0) for k, v in phase_summaries().items()}
        util = (
            self._budget_spent_total / self._budget_total
            if self._budget_total else 0.0
        )
        acc = (
            self.spec_accepted / self.spec_proposed
            if self.spec_proposed else None
        )
        knobs = {
            "prefill_chunk": self.prefill_chunk,
            "token_budget": self.token_budget,
            "spec_len": self.spec_len,
        }
        changes = ap.step(phases, util, acc, knobs)
        if not changes:
            return
        for knob, value in changes.items():
            setattr(self, knob, value)
        self.flight.record("autopilot", **{f"set_{k}": v for k, v in changes.items()})
        REGISTRY.counter_add(
            "acp_engine_autopilot_adjustments_total", 1.0,
            help="scheduler-knob adjustments applied by the autopilot "
            "(prefill_chunk / token_budget / spec_len steered from phase "
            "attribution, budget utilization and spec acceptance)",
        )
        log.info("autopilot adjusted knobs: %s", changes)

    def _stall_check(self, dt: float) -> None:
        """Dispatch watchdog: ``dt`` is the full busy-cycle wall time
        (fault throttles included); a cycle over ``stall_mult`` x the
        replica's normal cadence *and* over ``stall_min_s`` is a stall.
        The cadence baseline is the MIN busy-cycle time seen
        (``_cycle_floor``) — one-sided, so a slow cycle can never mask
        later stalls the way a compile-polluted EWMA would. Also
        publishes the EWMA mirror the cross-thread stats surface (and
        the fleet health sampler behind it) reads."""
        self._cycle_s = self._cycle_clock.cycle_s
        if dt > 0 and (self._cycle_floor == 0.0 or dt < self._cycle_floor):
            self._cycle_floor = dt
        base = self._cycle_floor
        if base <= 0.0 or dt < self.stall_min_s or dt < self.stall_mult * base:
            return
        self.stalls += 1
        self.flight.record("stall", cycle_s=round(dt, 4), floor_s=round(base, 5))
        REGISTRY.counter_add(
            "acp_engine_stalls_total", 1.0,
            help="dispatch cycles the engine-side watchdog judged stalled "
            "(wall time over stall_mult x the cycle-cadence EWMA and over "
            "stall_min_s) — the gray-failure signal the fleet health "
            "state machine consumes",
        )

    def _brownout_tick(self) -> None:
        """Degradation ladder (engine/brownout.py): on interval
        boundaries, judge shed/stall pressure and move at most one rung.
        Stepping DOWN saves and sheds the next optional knob in the
        pinned order (spec_len -> park acceptance -> chunk quota);
        stepping UP restores the most recent one. Mirrors the autopilot's
        apply-seam: the controller decides, the engine applies the knob
        and flight-records it, and the gauge tracks the level."""
        bo = self._brownout
        if bo is None or not bo.due():
            return
        from .brownout import LADDER

        target = bo.step(self.sheds, self.stalls)
        if target == self._brownout_level:
            return
        if target > self._brownout_level:
            knob, downed = LADDER[self._brownout_level]
            self._brownout_saved[knob] = getattr(self, knob)
            setattr(self, knob, downed)
            self._brownout_level += 1
            self.flight.record(
                "brownout", level=self._brownout_level, **{f"set_{knob}": downed}
            )
        else:
            knob, _ = LADDER[self._brownout_level - 1]
            restored = self._brownout_saved.pop(knob, getattr(self, knob))
            setattr(self, knob, restored)
            self._brownout_level -= 1
            self.flight.record(
                "brownout", level=self._brownout_level, **{f"set_{knob}": restored}
            )
        REGISTRY.gauge_set(
            "acp_engine_brownout_level", float(self._brownout_level),
            help="current rung of the degradation ladder (0 = full "
            "service; 1 = speculation off; 2 = + park acceptance off; "
            "3 = + chunk quota floored) — engine/brownout.py",
        )
        log.info("brownout level -> %d", self._brownout_level)

    def _has_work(self) -> bool:
        """Anything the dispatch loop must advance: decoding or mid-prefill
        slots (parked slots are speculative capacity, not work)."""
        return len(self._slots) - self._parked_count > 0

    def _dispatch_once(self) -> None:
        """One unified scheduler cycle. Chunked-off (or nothing mid-
        prefill): exactly the legacy decode iteration. Chunked-on: spend the
        per-cycle token budget across pending prefill chunks (deadline-
        weighted order) and the decode/verify dispatch. Policy guarantees,
        pinned by tests: decode dispatches EVERY cycle active slots exist
        (never starved by pending chunks), and at least one chunk advances
        per cycle (a tight budget throttles prefill, never deadlocks it)."""
        t0 = time.monotonic()
        if not self._prefilling_count:
            # chunked off, or nothing mid-prefill: the legacy decode
            # iteration. Keyed on _prefilling_count, not the knob: slots
            # admitted as prefilling must drain through the chunk loop even
            # if prefill_chunk was toggled off mid-flight (benches/tests
            # A/B the knob on a live engine).
            self._decode_once()
            self._cycle_clock.observe(time.monotonic() - t0)
            return
        self._apply_cancels()
        self._expire_prefilling()
        n_active = self._n_active()
        decode_reserve = n_active * self.decode_block_size
        budget = self.token_budget or (
            decode_reserve + self._chunk_tokens() * max(1, self._prefilling_count)
        )
        spent = self._prefill_chunks(max(0, budget - decode_reserve))
        if self._n_active() or self._fuse_pending is not None:
            # a fused cycle enters the decode site even with nothing
            # decoding: the pending chunk lanes flush as a chunks-only
            # megastep there
            steps0 = self.decode_steps
            self._decode_once()
            if self.decode_steps > steps0:
                # block path advances K steps, a verify dispatch 1 — count
                # the dispatch's compute rows (estimate; utilization is an
                # observability aid, not an accounting invariant)
                spent += n_active * min(
                    self.decode_steps - steps0, self.decode_block_size
                )
        self._cycle_clock.observe(time.monotonic() - t0)
        self._budget_last = (budget, spent)
        self._budget_spent_total += spent
        self._budget_total += budget
        REGISTRY.gauge_set(
            "acp_engine_token_budget_utilization",
            min(1.0, spent / budget) if budget else 0.0,
            help="tokens dispatched last scheduler cycle / per-cycle token "
            "budget (chunked prefill mode)",
        )

    def _apply_cancels(self) -> None:
        """Free slots whose requests were cancelled (shared by the decode
        path and the chunked scheduler — a cancelled mid-prefill slot must
        release its partial KV before more chunks are spent on it)."""
        if not self._applied_cancels:
            return
        for slot, sl in list(self._slots.items()):
            if sl.request.rid in self._applied_cancels:
                self._finish(slot, "cancelled")

    def _expire_prefilling(self) -> None:  # acp: leader-local
        """Deadline expiry for mid-prefill slots: release the partial KV
        and fail the request — spending more chunks on a dead deadline is
        pure waste. Same coordination discipline as _expire_deadlines:
        single-host releases in place; the leader resolves the future
        host-locally and routes the release through the replicated cancel
        stream; followers never expire on wall clock."""
        if self._coord_follower:
            return
        now = time.monotonic()
        expired = [
            (s, sl) for s, sl in self._slots.items()
            if sl.prefilling
            and sl.request.deadline is not None
            and now > sl.request.deadline
            and not sl.request.future.done()
        ]
        for slot, sl in expired:
            req = sl.request
            req.future.set_exception(DeadlineExceededError(
                "deadline expired mid-prefill (partial prompt KV released)"
            ))
            REGISTRY.counter_add("acp_engine_deadline_expired_total", 1.0)
            self._record_expire(req, "mid_prefill")
            if self._coordination is not None:
                self._cancelled.add(req.rid)  # rides the next published frame
            else:
                # offload the partial prompt KV before it is dropped — a
                # control-plane retry of the same task prefix-matches it
                if not self._swap_out(slot, sl, reason="expire") and not req.prewarm:
                    # dropped outright: the chunks already spent are waste
                    self.profiler.reclassify("preempt_discard", sl.prefill_pos)
                self._drop_prefilling_slot(slot)

    def _drop_prefilling_slot(self, slot: int) -> _Slot:
        """Release a mid-prefill slot's bookkeeping (partial KV pages, host
        mirrors, slot id). The caller owns resolving/requeueing the
        request."""
        sl = self._slots.pop(slot)
        self._prefilling_count -= 1
        self._unshare_followers(slot, sl)
        self._state_dirty = True
        self._seq_lens[slot] = 0
        self._last_tokens[slot] = 0
        self._con_states[slot] = 0
        self._constrained[slot] = False
        heapq.heappush(self._free, slot)
        if self.kv_layout == "paged":
            self._allocator.free(self._slot_pages.pop(slot, []))
            self._block_tables[slot, :] = TRASH_PAGE
            self._tables_dirty = True
        return sl

    def _use_megastep(self) -> bool:
        """Fused dispatch applies: the knob is on and the cycle has chunk
        work to fuse with the decode/verify dispatch. The non-chunked
        engine never fuses — its cycle is already one dispatch."""
        return self.megastep

    def _slot_chunk_tokens(self, sl: _Slot, CHK: int) -> int:
        """Per-cycle chunk size for one mid-prefill slot. The rate
        planner's quota (chunks/cycle, engine/planner.py) collapses into
        ONE larger continuation lane of quota*CHK tokens rather than
        quota separate lanes — consecutive chunks of a slot cannot be
        lanes of the same fused dispatch (the later lane would gather KV
        rows the earlier lane writes in the same program), and one bigger
        bucket is cheaper than quota dispatches in the split path too.
        Capped at the largest compiled prefill bucket; CHK and the
        buckets are page multiples, so paged alignment is preserved."""
        q = sl.chunk_quota if self.rate_planner else 1
        return min(max(1, q) * CHK, self.prefill_buckets[-1])

    def _chunk_items(self, batch: list) -> list:
        """(slot, sl, start, n) chunk tuples -> _prefill_group items."""
        paged = self.kv_layout == "paged"
        return [
            (sl.request, slot,
             self._slot_pages.get(slot) if paged else None, None)
            for slot, sl, _st, _n in batch
        ]

    def _run_restores(
        self, restores: list, defer: bool = False
    ) -> tuple[set, int, list]:
        """Dispatch or stage-commit this round's host-tier swap-in rows.
        The blocking path issues the host->device copies immediately; a
        chunk whose rows were prefetched last cycle (_stage_swap_in)
        instead commits the already-staged device arrays — with
        ``defer=True`` (a fused cycle) the staged scatter rides the
        megastep as its swaps phase, so the deferred entries
        ``(slot, sl, st, n, groups)`` come back for _megastep_dispatch /
        _dispatch_pending_split to land. Returns ``(aborted_slots,
        refunded_tokens, deferred)``: a restore the
        ``engine.host_swap_error`` fault cancelled dispatched nothing, so
        its budget refunds and it stays out of the round's flight/counter
        record; a stage the ``engine.prefetch_error`` fault aborts (or a
        stale/mismatched stage) degrades to the blocking copy, byte-
        identically — the scatter writes the same rows either way."""
        aborted: set[int] = set()
        refund = 0
        deferred: list = []
        if not restores:
            return aborted, refund, deferred
        with self._hol_clock():
            for slot, sl, st, n in restores:
                if self._faults.enabled and st == 0:
                    spec = self._faults.pop("engine.host_swap_slow")
                    if spec is not None:
                        slow = float(spec.get("seconds", 0.05))
                        time.sleep(slow)
                        sl.swap_stall_s += slow  # attributed as host_stall
                    if self._faults.pop("engine.host_swap_error") is not None:
                        # restore "failed" before any rows landed: fall
                        # back to recomputing the whole prefill (the entry
                        # was consumed; byte-identity is unaffected)
                        self.flight.record(
                            "swap_in", rid=sl.request.rid, slot=slot,
                            error=True,
                        )
                        # the preserved rows now get recomputed by model
                        # chunks after all — host-swap-error recompute waste
                        self.profiler.reclassify(
                            "swap_recompute", self._swap_in_cut(sl)
                        )
                        sl.swap_entry = None
                        sl.swap_staged = None
                        aborted.add(slot)
                        refund += n
                        continue
                staged, sl.swap_staged = sl.swap_staged, None
                use_staged = (
                    staged is not None
                    and staged["start"] == st
                    and staged["n"] == n
                )
                if use_staged and self._faults.enabled:
                    if self._faults.pop("engine.prefetch_error") is not None:
                        # aborted async stage: drop the staged copies and
                        # run the blocking swap-in — same bytes land, only
                        # the overlap (and its stall saving) is lost
                        self.flight.record(
                            "prefetch_abort", rid=sl.request.rid, slot=slot,
                            start=st,
                        )
                        use_staged = False
                if use_staged and defer:
                    deferred.append((slot, sl, st, n, staged["groups"]))
                    continue
                if use_staged:
                    sl.swap_stall_s += self._commit_staged_swap(
                        staged["groups"]
                    )
                else:
                    sl.swap_stall_s += self._swap_in_rows(
                        slot, sl.swap_entry, st, n
                    )
                self._advance_restore(slot, sl, st, n)
        return aborted, refund, deferred

    def _advance_restore(self, slot: int, sl: _Slot, st: int, n: int) -> None:
        """Post-commit bookkeeping for one restore chunk (shared by the
        blocking path, the staged split commit, and the megastep's swaps-
        phase commit): advance the host mirrors, finish the swap-in at the
        cut, and otherwise stage the NEXT chunk's rows so the copy
        overlaps the rest of this cycle's compute."""
        sl.prefill_pos = st + n
        self._seq_lens[slot] = sl.prefill_pos
        if sl.prefill_pos >= self._swap_in_cut(sl):
            self._finish_swap_in(slot, sl)
        elif self.host_prefetch and self.kv_layout == "paged":
            self._stage_swap_in(slot, sl)

    def _commit_staged_swap(self, groups: list) -> float:  # acp: megastep-seam # acp: kv-seam # acp: swap-stage
        """Commit half of the prefetch split (split-dispatch form): scatter
        the staged device arrays into the pages with the SAME jitted
        scatter the blocking path uses — ids and blocks hold identical
        values, so the cache bytes are identical; the host->device copy
        already overlapped last cycle's compute, so the only blocking cost
        left is the dispatch itself."""
        t0 = time.monotonic()
        P = self.page_size
        for ids, blocks in groups:
            m = int(ids.shape[0])
            fn = self._jit_swap_scatter.get(m)
            if fn is None:
                fn = jax.jit(
                    lambda c, ids, blocks: {
                        name: c[name].at[:, ids].set(blocks[name])
                        for name in c
                    },
                    donate_argnums=(0,),
                )
                self._jit_swap_scatter[m] = fn
            prof_t0 = self.profiler.start()
            self.cache = fn(self.cache, ids, blocks)
            self.profiler.record(
                f"swap_scatter[{m}]", prof_t0, out=self.cache["k"],
                real_tokens=m * P,
            )
        REGISTRY.counter_add(
            "acp_engine_kv_prefetch_commits_total", 1.0,
            help="host-KV restore chunks whose rows were prefetched (staged "
            "host->device a cycle early) and landed by scatter commit — the "
            "async-prefetch overlap win; chunks NOT counted here paid the "
            "blocking copy as host_stall",
        )
        return time.monotonic() - t0

    def _stage_swap_in(self, slot: int, sl: _Slot) -> None:  # acp: swap-stage
        """Stage half of the prefetch split: slice the NEXT restore
        chunk's host rows and launch them host->device with non-blocking
        device puts, in the same pow2 page groups the blocking
        _swap_in_rows would scatter. Nothing is committed — the pages are
        untouched until the commit half lands the scatter inside the next
        cycle's dispatch window, so an invalidated slot (preempt/cancel)
        simply drops the staged arrays. Paged layout only: the slot
        layout's dynamic_update_slice restore stays blocking."""
        entry = sl.swap_entry
        start = sl.prefill_pos
        n = min(
            self._slot_chunk_tokens(sl, self._chunk_tokens()),
            self._swap_in_cut(sl) - start,
        )
        if n <= 0:
            sl.swap_staged = None
            return
        rows = {"k": entry.k, "v": entry.v}
        if "ks" in self.cache:
            rows["ks"] = entry.k_scale
            rows["vs"] = entry.v_scale
        P = self.page_size
        pages = self._slot_pages[slot][start // P : (start + n) // P]
        groups: list = []
        i = 0
        for m in _pow2_sizes(len(pages)):
            ids = np.asarray(pages[i : i + m], dtype=np.int32)
            lo = start + i * P
            blocks = {
                name: a[:, lo : lo + m * P].reshape(
                    a.shape[0], m, P, *a.shape[2:]
                )
                for name, a in rows.items()
            }
            groups.append((
                self._put(ids),
                {name: self._put(b) for name, b in blocks.items()},
            ))
            i += m
        sl.swap_staged = {"start": start, "n": n, "groups": groups}

    def _record_chunk_round(
        self, landed: list, spent: int, budget: int, restore_slots: set
    ) -> None:
        """One round's chunk bookkeeping, shared by the split path and the
        megastep commit: per-chunk flight events (only chunks that really
        dispatched), the round's budget-spend event, and the counters."""
        self.prefill_chunks += len(landed)
        if self.flight.enabled:
            # the EDF/quota pick + budget spend this cycle: one event per
            # chunk that actually dispatched plus the round's accounting
            for slot, sl, st, n in landed:
                if not sl.request.prewarm:
                    self.flight.record(
                        "prefill_chunk", rid=sl.request.rid, slot=slot,
                        start=st, n=n,
                        final=st + n >= len(sl.prefill_row or ()),
                        swap=slot in restore_slots,
                    )
            self.flight.record(
                "prefill_round", scheduled=len(landed), spent=spent,
                budget=budget,
            )
        REGISTRY.counter_add(
            "acp_engine_prefill_chunks_total", float(len(landed)),
            help="prefill chunk dispatches (per-slot chunks) under the "
            "unified token-budget scheduler",
        )

    def _prefill_chunks(self, chunk_budget: int) -> int:
        """One scheduler round of chunked prefill: give each mid-prefill
        slot its planned per-cycle chunk (the rate planner's quota; one
        base chunk without a deadline), in deadline-weighted order
        (earliest deadline first, then admission order; under multi-host
        coordination deadlines are leader-local wall clock, so ordering
        falls back to admission order — the same lockstep rule as deadline
        expiry), until the chunk budget is spent. The first chunk always
        dispatches even over budget (minimum-progress guarantee).
        Non-final chunks write KV only; a final chunk samples the slot's
        first token and flips it to decoding via the shared _prefill_group
        path. With the megastep enabled, mid chunks and continuation
        finals are PLANNED here but dispatch fused with this cycle's
        decode/verify program (_fuse_pending -> _megastep_dispatch);
        plain finals (start 0) keep the plain causal program — byte-for-
        byte the chunked-off dispatch — and still join this cycle's
        decode lanes. Returns tokens spent."""
        pre = [(s, sl) for s, sl in self._slots.items() if sl.prefilling]
        if not pre:
            return 0
        if self._faults.enabled:
            # deterministic mid-prefill preemption: lands on the PARTIALLY
            # prefilled slot with the most progress (steps = total chunks
            # dispatched, so after_steps=N lets N chunks land first)
            spec = self._faults.pop(
                "engine.preempt_mid_prefill", steps=self.prefill_chunks
            )
            if spec is not None:
                victim = max(pre, key=lambda t: (t[1].prefill_pos, t[0]))[0]
                self._preempt(victim, reason="fault")
                pre = [(s, sl) for s, sl in self._slots.items() if sl.prefilling]
                if not pre:
                    return 0
        if self._coordination is None:
            pre.sort(key=lambda t: (
                t[1].request.deadline
                if t[1].request.deadline is not None else float("inf"),
                t[1].admit_seq,
            ))
        else:
            pre.sort(key=lambda t: t[1].admit_seq)
        # dedup followers whose leader hasn't written the shared rows yet
        # WAIT (no chunk, no budget) — dispatching their suffix would read
        # garbage below the cut. A leader that finished its prefill (or
        # whose death already rewound this follower) clears the latch.
        ready: list[tuple[int, _Slot]] = []
        for slot, sl in pre:
            if sl.share_of is not None:
                lead = self._slots.get(sl.share_of[0])
                if (
                    lead is not None
                    and lead.prefilling
                    and lead.request.rid == sl.share_of[1]
                    and lead.prefill_pos < sl.share_of[2]
                ):
                    continue
                sl.share_of = None  # shared rows written; follower proceeds
            ready.append((slot, sl))
        pre = ready
        if not pre:
            return 0
        CHK = self._chunk_tokens()
        sched: list[tuple[int, _Slot, int, int]] = []  # (slot, sl, start, n)
        spent = 0
        for slot, sl in pre:
            cap = self._slot_chunk_tokens(sl, CHK)
            if sl.swap_entry is not None:
                # a swapped chunk costs budget like a prefill chunk (EDF-
                # ordered with them): the restore copy competes for the
                # same cycle the model chunks would
                n = min(cap, self._swap_in_cut(sl) - sl.prefill_pos)
            else:
                n = min(cap, len(sl.prefill_row) - sl.prefill_pos)
            if sched and spent + n > chunk_budget:
                break  # budget spent; later (EDF-ordered) slots wait a cycle
            sched.append((slot, sl, sl.prefill_pos, n))
            spent += n
        restores = [c for c in sched if c[1].swap_entry is not None]
        restore_slots = {c[0] for c in restores}
        model = [c for c in sched if c[1].swap_entry is None]
        mids = [c for c in model if c[2] + c[3] < len(c[1].prefill_row)]
        finals = [c for c in model if c[2] + c[3] >= len(c[1].prefill_row)]
        # finals whose whole row fits one chunk (start 0) take the plain
        # causal program — byte-for-byte the chunked-off dispatch; only
        # true continuations need the offset program
        plain = [c for c in finals if c[2] == 0]
        conts = [c for c in finals if c[2] > 0]
        paged = self.kv_layout == "paged"
        staged_ready = any(
            c[1].swap_staged is not None
            and c[1].swap_staged["start"] == c[2]
            and c[1].swap_staged["n"] == c[3]
            for c in restores
        )
        fused = self._use_megastep() and (
            mids or conts or (paged and plain) or staged_ready
        )
        aborted_slots, refund, deferred = self._run_restores(
            restores, defer=bool(fused and paged)
        )
        spent -= refund
        if fused:
            # fused cycle: mid chunks, continuation finals — and on the
            # paged layout plain (start-0) finals plus prefetch-staged
            # restore scatters — defer into the single fused program the
            # decode/verify site dispatches (_megastep_dispatch). Their
            # commit bookkeeping (prefill_pos, flight, counters) rides the
            # megastep commit so nothing is recorded that didn't dispatch.
            # Slot-layout plain finals still dispatch immediately (and join
            # this very cycle's decode lanes, as in the split path); an
            # absorbed plain samples its first token INSIDE the megastep,
            # so it joins the NEXT cycle's lanes — a scheduling shift only,
            # greedy bytes are unchanged.
            plains_pend: list = plain if paged else []
            if not paged:
                with self._hol_clock():
                    for batch in _pow2_chunks(plain, self.prefill_batch_max):
                        self._prefill_group(self._chunk_items(batch))
            deferred_keys = {(c[0], c[2]) for c in deferred}
            landed_now = [
                c for c in sched
                if c[0] not in aborted_slots
                and (
                    (c in plain and not paged)
                    or (
                        c[0] in restore_slots
                        and (c[0], c[2]) not in deferred_keys
                    )
                )
            ]
            self._fuse_pending = {
                "mids": mids, "finals": conts, "plains": plains_pend,
                "swaps": deferred, "landed": landed_now,
                "spent": spent, "budget": chunk_budget,
                "restores": restore_slots,
            }
            return spent
        with self._hol_clock():
            for batch in _pow2_chunks(mids, self.prefill_batch_max):
                self._chunk_dispatch(batch)
            for batch in _pow2_chunks(plain, self.prefill_batch_max):
                self._prefill_group(self._chunk_items(batch))
            for batch in _pow2_chunks(conts, self.prefill_batch_max):
                self._prefill_group(
                    self._chunk_items(batch),
                    starts_np=np.asarray([st for _, _, st, _ in batch], dtype=np.int32),
                )
        for slot, sl, st, n in mids:
            sl.prefill_pos = st + n
            self._seq_lens[slot] = sl.prefill_pos
        landed = [c for c in sched if c[0] not in aborted_slots]
        self._record_chunk_round(landed, spent, chunk_budget, restore_slots)
        return spent

    def _chunk_dispatch(  # acp: megastep-seam — split chunk program (fused fallback)
        # acp: dispatch-lanes toks,lengths,starts,slots,page_ids
        self, batch: list[tuple[int, "_Slot", int, int]]
    ) -> None:
        """One batched KV-only chunk dispatch (the per-cycle analogue of
        _spill_long_chunks' rounds): each row runs tokens [start, start+n)
        of its slot's prefill row through the continuation program, writing
        KV without sampling. Rows may have different lengths (final-size
        remainders never land here, but budget clipping is caller policy)."""
        B = len(batch)
        self._chunk_batch_sizes.add(B)
        bucket = _next_bucket(max(n for _, _, _, n in batch), self.prefill_buckets)
        toks = np.zeros((B, bucket), dtype=np.int32)
        lengths = np.zeros(B, dtype=np.int32)
        starts = np.zeros(B, dtype=np.int32)
        slots = np.zeros(B, dtype=np.int32)
        for i, (slot, sl, st, n) in enumerate(batch):
            toks[i, :n] = sl.prefill_row[st : st + n]
            lengths[i] = n
            starts[i] = st
            slots[i] = slot
        self._rng, step_rng = jax.random.split(self._rng)
        tail = (
            step_rng,
            self._put(np.zeros(B, dtype=np.float32)),  # temps (sample unused)
            self._put(np.zeros(B, dtype=np.int32)),
            self._put(np.ones(B, dtype=np.float32)),
            self._dummy_table,
            self._put(np.zeros(B, dtype=np.int32)),
            self._put(np.zeros(B, dtype=bool)),  # unconstrained
            self._dummy_min_close,
            self._put(np.ones(B, dtype=np.int32)),
        )
        prof_t0 = self.profiler.start()
        if self.kv_layout == "paged":
            P = self.page_size
            page_ids = np.full((B, bucket // P), TRASH_PAGE, dtype=np.int32)
            for i, (slot, _sl, st, n) in enumerate(batch):
                # chunk boundaries are page-aligned (see _chunk_tokens), so
                # the commit's whole-page writes touch exactly this chunk's
                # fresh pages — never a page holding earlier KV
                sub = self._slot_pages[slot][st // P : -(-(st + n) // P)]
                page_ids[i, : len(sub)] = sub
            block_tables = self._put(
                self._block_tables[[slot for slot, _, _, _ in batch]]
            )
            self.cache, _tok, _state = self._jit_prefill_paged_continue(
                self.params,
                self.cache,
                self._put(toks),
                self._put(lengths),
                self._put(starts),
                self._put(page_ids),
                block_tables,
                *tail,
            )
        else:
            self.cache, _tok, _state = self._jit_prefill_continue(
                self.params,
                self.cache,
                self._put(toks),
                self._put(lengths),
                self._put(starts),
                self._put(slots),
                *tail,
            )
        if self.profiler.enabled:
            real = int(lengths.sum())
            self.profiler.record(
                f"chunk[{self.kv_layout},{bucket}x{B}]", prof_t0, out=_tok,
                real_tokens=real, padded_tokens=B * bucket - real,
                real_slots=B,
            )
            pre = sum(n for _, sl, _, n in batch if sl.request.prewarm)
            self.profiler.account(
                goodput=real - pre, prewarm=pre, pad_bucket=B * bucket - real
            )

    # -- prefix KV cache (slot layout) -----------------------------------

    @staticmethod
    def _full_row(req: _Request) -> list[int]:
        """The tokens a request prefills: prompt + teacher-forced prefix,
        plus — after a preemption — everything it had already sampled, so
        the resumed decode continues exactly where it left off."""
        return (
            list(req.prompt)
            + list(req.sampling.forced_prefix)
            + list(req.resume_tokens)
        )

    def _match_prefix(self, req: _Request) -> Optional[tuple]:
        """Longest cached entry whose key is a strict prefix of the row
        (strict: at least one suffix token must remain to produce logits)."""
        if req.truncated:
            return None
        full = self._full_row(req)
        with self._prefix_lock:
            best_key, best = None, None
            for key, entry in self._prefix_cache.items():
                cut = entry["cut"]
                if cut < len(full) and (best is None or cut > best["cut"]):
                    if tuple(full[:cut]) == key:
                        best_key, best = key, entry
            if best_key is None:
                return None
            self._prefix_cache.move_to_end(best_key)
            return (best_key, best)

    def _copy_prefix_into_slot(self, slot: int, entry: dict) -> None:  # acp: megastep-seam # acp: kv-seam
        cut = entry["cut"]
        fn = self._jit_copy_prefix.get(cut)
        if fn is None:

            def copy(cache, slot_, rows):
                # dict-generic over the cache's keys so a quantized cache's
                # scale rows ("ks"/"vs", one rank lower) copy with the values
                return {
                    name: jax.lax.dynamic_update_slice(
                        arr, rows[name][:, None],
                        (0, slot_) + (0,) * (arr.ndim - 2),
                    )
                    for name, arr in cache.items()
                }

            fn = jax.jit(copy, donate_argnums=(0,))
            self._jit_copy_prefix[cut] = fn
        prof_t0 = self.profiler.start()
        self.cache = fn(
            self.cache, jnp.int32(slot),
            {name: entry[name] for name in self.cache},
        )
        self.profiler.record(
            f"prefix_copy[{cut}]", prof_t0, out=self.cache["k"],
            real_tokens=cut, real_slots=1,
        )

    def _save_prefix(self, full: list[int], prompt_len: int, slot: int) -> None:  # acp: megastep-seam # acp: kv-seam
        """After a prefill: snapshot the slot's leading KV as a reusable
        prefix entry (LRU-capped). Slot layout: a device COPY at the largest
        bucket/chunk boundary. Paged layout: zero-copy — take a reference on
        the slot's leading (full, immutable) pages. The cut never reaches
        past the PROMPT into the teacher-forced generation prefix — the
        next turn's rendered prompt contains the serialized assistant
        message, not the raw forced tokens, so a key crossing that boundary
        could never match again."""
        if not self._prefix_enabled:
            return
        cap = min(prompt_len, len(full) - 1)
        if self.kv_layout == "paged":
            cut = (cap // self.page_size) * self.page_size  # full pages only
        else:
            cut = 0
            for b in self.prefill_buckets:
                if b <= cap:
                    cut = b
            # chunked-prefill configs (largest bucket << max_ctx): snapshot
            # at the largest chunk-multiple instead, or long conversations
            # would be reusable only up to one bucket
            CH = self.prefill_buckets[-1]
            cut = max(cut, (cap // CH) * CH)
        if cut < min(self.prefill_buckets[0], 4 * self.page_size):
            return  # too short to be worth caching
        key = tuple(full[:cut])
        with self._prefix_lock:
            if key in self._prefix_cache:
                self._prefix_cache.move_to_end(key)
                return
        if self.kv_layout == "paged":
            pages = self._slot_pages[slot][: cut // self.page_size]
            self._allocator.share(pages)
            entry = {"cut": cut, "pages": list(pages)}
        else:
            fn = self._jit_extract_prefix.get(cut)
            if fn is None:
                L = self.config.n_layers

                def extract(cache, slot_):
                    # dict-generic: values [L, cut, H, d] and (quantized)
                    # scale rows [L, cut, H] slice with the same indices
                    return {
                        name: jax.lax.dynamic_slice(
                            arr,
                            (0, slot_) + (0,) * (arr.ndim - 2),
                            (L, 1, cut) + arr.shape[3:],
                        )[:, 0]
                        for name, arr in cache.items()
                    }

                fn = jax.jit(extract)  # read-only: cache NOT donated
                self._jit_extract_prefix[cut] = fn
            prof_t0 = self.profiler.start()
            rows = fn(self.cache, jnp.int32(slot))
            self.profiler.record(
                f"prefix_extract[{cut}]", prof_t0, out=rows["k"],
                real_tokens=cut, real_slots=1,
            )
            entry = {"cut": cut, **rows}
        with self._prefix_lock:
            self._prefix_cache[key] = entry
            while len(self._prefix_cache) > self._prefix_cache_entries or (
                len(self._prefix_cache) > 1
                and self._cached_tokens_locked() > self._prefix_cache_max_tokens
            ):
                _, old = self._prefix_cache.popitem(last=False)  # evict LRU
                if "pages" in old:
                    self._allocator.free(old["pages"])  # drop the cache ref

    def _cached_tokens_locked(self) -> int:
        """Distinct tokens pinned by the cache (hold _prefix_lock). Paged
        entries from one growing conversation SHARE pages — counting each
        entry's cut would double-count them and evict prematurely."""
        toks = 0
        pages: set[int] = set()
        for e in self._prefix_cache.values():
            if "pages" in e:
                pages.update(e["pages"])
            else:
                toks += e["cut"]
        return toks + len(pages) * self.page_size

    def _evict_one_prefix_entry(self) -> bool:
        """Evict the LRU prefix entry (allocation pressure). True if one
        was evicted."""
        with self._prefix_lock:
            if not self._prefix_cache:
                return False
            _, old = self._prefix_cache.popitem(last=False)
        if "pages" in old:
            self._allocator.free(old["pages"])
        return True

    def _collect_group(self) -> list[tuple[_Request, int, Optional[list[int]], Optional[tuple]]]:
        """Pop up to prefill_batch_max admissible head requests, reserving a
        slot (and KV pages, in paged mode) for each, and resolving each
        request's prefix-cache match. Paged hits assemble their block list
        as SHARED prefix pages (refcounted, never re-written) + freshly
        allocated suffix pages. Strict FIFO: stop at the first request that
        can't get pages."""
        group: list[tuple[_Request, int, Optional[list[int]], Optional[tuple]]] = []
        while (
            self._waiting
            and len(group) < self.prefill_batch_max
            and (self._free or self._has_parked())
        ):
            req = self._waiting[0]
            s = req.sampling
            # queued-deadline expiry happens in _expire_deadlines, which
            # _admit runs (and the leader publishes) before every
            # _fill_slots — by here the head of the deque is live
            if s.json_only and s.forced_prefix:
                # seed the automaton past the forced prefix; an illegal
                # prefix can never complete, so fail it up front
                if self._seed_con_state(s.forced_prefix) < 0:
                    self._waiting.popleft()
                    if not req.prewarm:
                        self.flight.record(
                            "cancel", rid=req.rid, where="illegal_prefix"
                        )
                        self.flight.discard(req.rid)
                    req.future.set_exception(
                        RuntimeError("forced_prefix is not a legal JSON prefix")
                    )
                    continue
            match: Optional[tuple] = None
            if self._prefix_enabled and not req.truncated:
                match = self._match_prefix(req)
            full = self._full_row(req)
            # host-tier candidate: an exact-rid entry (preempt -> resume)
            # or the longest token-prefix entry (park expiry / deadline
            # drop whose conversation came back). Peek only — reservation
            # may still fail, so consumption waits for the commit below.
            host_e = None
            host_cut = 0
            if self._host_pool is not None and not req.truncated:
                host_e = self._host_pool.get(req.rid)
                if host_e is not None and not (
                    0 < host_e.cut < len(full)
                    and tuple(full[: host_e.cut]) == host_e.tokens
                ):
                    host_e = None
                if host_e is None:
                    host_e = self._host_pool.match_prefix(full)
                if host_e is not None:
                    host_cut = min(host_e.cut, len(full) - 1)
                    if self.kv_layout == "paged":
                        host_cut = (host_cut // self.page_size) * self.page_size
                    if host_cut < self._swap_min_rows():
                        host_e, host_cut = None, 0
            # dedup candidate: share a live slot's (or an earlier group
            # member's) prompt pages instead of materializing a copy
            dedup = self._match_dedup_leader(full, group) if not req.truncated else None
            # parked-slot adoption: a slot parked by this conversation's
            # previous turn holds its prompt KV in place — resume there
            # (suffix-only prefill, no copy). Candidate selection is by
            # covered rows, ties broken by mechanism cost: in-place
            # adoption beats a zero-copy cache share beats a dedup share
            # (which may wait on its leader) beats a host restore (which
            # pays a host->device copy).
            adopt = self._match_parked(req)
            best_cut, _prio, kind = max(
                (self._slots[adopt].park_cut if adopt is not None else 0, 3, "adopt"),
                (match[1]["cut"] if match is not None else 0, 2, "cache"),
                (dedup[2] if dedup is not None else 0, 1, "dedup"),
                (host_cut, 0, "host"),
            )
            if best_cut <= 0:
                kind = None
            if kind == "adopt":
                item = self._adopt_parked(req, adopt)
                if item is None:
                    break  # pages short even after yielding; head waits (FIFO)
                if item:
                    group.append(item[0])
                continue  # oversize-prompt rejection popped the head
            # no adoption possible: parked capacity yields a free slot —
            # preferring NOT to release the dedup leader itself (its pages
            # are the share). If the leader is the only parked capacity,
            # release it anyway; the dedup branch below demotes a vanished
            # leader to a plain undeduped admission.
            if not self._free and not self._release_lru_parked(
                exclude=dedup[0] if dedup is not None else None
            ):
                if not self._release_lru_parked():
                    break
            pages: Optional[list[int]] = None
            shared: list[int] = []
            if self.kv_layout == "paged":
                total_pages = -(-len(full) // self.page_size)
                if self._reject_oversize_head(req, total_pages):
                    continue
                if kind == "cache":
                    shared = list(match[1]["pages"])
                elif kind == "dedup":
                    leader_pages = self._slot_pages.get(dedup[0])
                    if leader_pages is None:  # leader reserved in THIS group
                        leader_pages = next(
                            (it[2] for it in group if it[1] == dedup[0]), None
                        )
                    if leader_pages is None:
                        # the leader vanished between selection and
                        # reservation (released for its slot id above):
                        # admit undeduped rather than crash or mis-share
                        kind = None
                    else:
                        shared = list(
                            leader_pages[: best_cut // self.page_size]
                        )
                # take the share FIRST: if allocation pressure evicts the
                # matched entry below, our reference keeps its pages alive
                self._allocator.share(shared)
                fresh: Optional[list[int]] = None
                while fresh is None:
                    try:
                        fresh = self._allocator.alloc(total_pages - len(shared))
                    except MemoryError:
                        # parked slots yield first (speculative capacity for
                        # ONE possible future turn), then cache entries —
                        # under pressure both must give way or an idle
                        # engine could livelock with the head request
                        # waiting on pages nothing will free
                        if self._release_lru_parked():
                            continue
                        if not self._evict_one_prefix_entry():
                            break
                if fresh is None:
                    self._allocator.free(shared)  # undo; head waits (FIFO)
                    break
                pages = shared + fresh
            if kind == "dedup":
                match = (None, {"cut": best_cut, "share_of": (dedup[0], dedup[1])})
                self.prefix_shares += 1
                if not req.prewarm:
                    self.flight.record(
                        "prefix_share", rid=req.rid, cut=best_cut,
                        leader=dedup[1], pages=len(shared),
                    )
            elif kind == "host":
                # reservation held: consume the entry (its bytes return to
                # the host budget; the restore is scheduled chunk by chunk)
                self._host_pool.pop(host_e.rid)
                match = (None, {"cut": best_cut, "swap": host_e})
            elif kind is None:
                match = None
            self._waiting.popleft()
            # lowest-index slot first: keeps active slots compacted at low
            # indices so decode width bucketing stays narrow
            group.append((req, heapq.heappop(self._free), pages, match))
        return group

    def _seed_con_state(self, prefix: Sequence[int]) -> int:
        """Walk the token table over a forced prefix; -1 = illegal."""
        self._get_token_table()  # ensure built
        state = self._table_start
        for tok in prefix:
            if state < 0 or tok >= self._token_table_np.shape[1]:
                return -1
            state = int(self._token_table_np[state, tok])
        return state

    def _get_token_table(self):
        """Lazy-build + cache the grammar token table on device. Called from
        the engine thread AND from caller threads (prewarm, bench setup), so
        the build is lock-serialized and ``_token_table`` is assigned LAST:
        readers that key on ``_token_table is not None`` (e.g. _decode_once's
        use_real) must never observe a half-built state where ``_min_close``
        is still None."""
        if self._token_table is None:
            with self._table_lock:
                if self._token_table is not None:
                    return self._token_table
                from .constrain import build_token_table

                t0 = time.monotonic()
                table = build_token_table(self.tokenizer)
                padded = np.full(
                    (table.token_trans.shape[0], self.config.vocab_size), -1, dtype=np.int32
                )
                width = min(self.config.vocab_size, table.token_trans.shape[1])
                padded[:, :width] = table.token_trans[:, :width]
                self._token_table_np = padded  # host-side walks (prefix seeding)
                self._min_close = self._put(table.min_close.astype(np.int32))
                self._table_start = table.start_state
                self._token_table = self._put(padded)  # LAST: publishes the rest
                log.info(
                    "built JSON constraint table: %d states x %d tokens in %.1fs",
                    *table.token_trans.shape, time.monotonic() - t0,
                )
        return self._token_table

    def _prefill_lanes(
        self, chunk: list, starts: np.ndarray
    ) -> dict:
        # acp: dispatch-lanes tokens,lengths,slots,temps,top_ks,top_ps,con_states0,constrained0,budgets,full_lens
        # acp: budget-seam — the ONE admission-time budget computation (the
        # +1-for-the-first-token form); decode/verify recomputation goes
        # through _slot_budget
        """Build the batched prefill/continuation lane arrays for B
        already-reserved requests — shared by the split _prefill_group
        dispatch and the megastep's fused final phase, so both upload the
        same numbers (the budget seam must have exactly one home)."""
        B = len(chunk)
        # bucket over what actually runs through the model (full row on a
        # miss; suffix on a hit)
        bucket = max(
            _next_bucket(len(self._full_row(r)) - int(starts[i]), self.prefill_buckets)
            for i, (r, _, _, _) in enumerate(chunk)
        )
        tokens = np.zeros((B, bucket), dtype=np.int32)
        lengths = np.zeros(B, dtype=np.int32)
        slots = np.zeros(B, dtype=np.int32)
        temps = np.zeros(B, dtype=np.float32)
        top_ks = np.zeros(B, dtype=np.int32)
        top_ps = np.ones(B, dtype=np.float32)
        con_states0 = np.zeros(B, dtype=np.int32)
        constrained0 = np.zeros(B, dtype=bool)
        budgets = np.zeros(B, dtype=np.int32)
        full_lens = np.zeros(B, dtype=np.int32)
        any_json = any(r.sampling.json_only for r, _, _, _ in chunk)
        if any_json:
            table = self._get_token_table()
            min_close = self._min_close
        else:
            table = self._token_table if self._token_table is not None else self._dummy_table
            min_close = (
                self._min_close if self._min_close is not None else self._dummy_min_close
            )
        for i, (req, slot, _, _m) in enumerate(chunk):
            s = req.sampling
            row = self._full_row(req)
            plen = len(row)
            full_lens[i] = plen
            suffix = row[int(starts[i]) :]
            tokens[i, : len(suffix)] = suffix
            lengths[i] = len(suffix)
            slots[i] = slot
            temps[i] = s.temperature
            top_ks[i] = s.top_k
            top_ps[i] = s.top_p
            # ctx-bounded: 1 token now + decode capacity to the ctx edge
            # (the decode block deactivates the slot device-side at max_ctx-1);
            # a resumed request's budget excludes what it already sampled
            budgets[i] = min(
                s.max_tokens - len(req.resume_tokens),
                1 + max(0, self.max_ctx - 1 - plen),
            )
            if s.json_only:
                seed = tuple(s.forced_prefix) + tuple(req.resume_tokens)
                con_states0[i] = self._seed_con_state(seed) if seed else self._table_start
                constrained0[i] = True
        return {
            "bucket": bucket, "tokens": tokens, "lengths": lengths,
            "slots": slots, "temps": temps, "top_ks": top_ks,
            "top_ps": top_ps, "con_states0": con_states0,
            "constrained0": constrained0, "budgets": budgets,
            "full_lens": full_lens, "table": table, "min_close": min_close,
        }

    def _prefill_group(  # acp: megastep-seam
        self,
        chunk: list[tuple[_Request, int, Optional[list[int]]]],
        starts_np: Optional[np.ndarray] = None,
    ) -> None:
        """One batched prefill dispatch for B already-reserved requests
        (B = power of two <= prefill_batch_max). Burst admissions no longer
        serialize: 64 arrivals are 8 dispatches of 8 prompts, not 64
        batch-1 prefills. With ``starts_np`` (prefix-cache hits and/or
        chunked-prefill remainders; slot KV below each start is already
        populated), only the SUFFIX runs through the model
        (prefill_continue)."""
        B = len(chunk)
        starts = starts_np if starts_np is not None else np.zeros(B, dtype=np.int32)
        ln = self._prefill_lanes(chunk, starts)
        bucket, full_lens, lengths = ln["bucket"], ln["full_lens"], ln["lengths"]
        table, min_close = ln["table"], ln["min_close"]
        if starts_np is None:
            self._full_batch_shapes.add((bucket, B))
        self._rng, step_rng = jax.random.split(self._rng)
        common = (
            self._put(ln["tokens"]),
            self._put(lengths),
        )
        tail = (
            step_rng,
            self._put(ln["temps"]),
            self._put(ln["top_ks"]),
            self._put(ln["top_ps"]),
            table,
            self._put(ln["con_states0"]),
            self._put(ln["constrained0"]),
            min_close,
            self._put(ln["budgets"]),
        )
        prof_t0 = self.profiler.start()
        if self.kv_layout == "paged":
            P = self.page_size
            # suffix pages only (the model writes just the suffix; shared
            # prefix pages are referenced via the block table, never written)
            # slot pages / block tables were installed at admission (they
            # must exist before spill chunks reference them)
            page_ids = np.full((B, bucket // P), TRASH_PAGE, dtype=np.int32)
            for i, (_req, _slot, pages, _m) in enumerate(chunk):
                assert pages is not None
                fresh = pages[int(starts[i]) // P :]
                page_ids[i, : len(fresh)] = fresh
            if starts_np is not None:
                self._cont_batch_sizes.add(B)
                block_tables = self._put(
                    self._block_tables[[slot for _, slot, _, _ in chunk]]
                )
                cache, firsts, con_states = self._jit_prefill_paged_continue(
                    self.params, self.cache, *common,
                    self._put(starts), self._put(page_ids), block_tables, *tail,
                )
            else:
                cache, firsts, con_states = self._jit_prefill_paged(
                    self.params, self.cache, *common, self._put(page_ids), *tail
                )
        elif starts_np is not None:
            self._cont_batch_sizes.add(B)
            cache, firsts, con_states = self._jit_prefill_continue(
                self.params, self.cache, *common,
                self._put(starts), self._put(ln["slots"]), *tail,
            )
        else:
            cache, firsts, con_states = self._jit_prefill(
                self.params, self.cache, *common, self._put(ln["slots"]), *tail
            )
        self.cache = cache
        if self.profiler.enabled:
            # program key mirrors the jit cache keying: kind x bucket x
            # batch x layout, +tbl once the real grammar table shape traces
            kind = "prefill_cont" if starts_np is not None else "prefill"
            tbl = "+tbl" if table is not self._dummy_table else ""
            real = int(lengths.sum())
            self.profiler.record(
                f"{kind}[{self.kv_layout},{bucket}x{B}{tbl}]", prof_t0,
                out=firsts, real_tokens=real,
                padded_tokens=B * bucket - real, real_slots=B,
            )
            pre = sum(
                int(lengths[i]) for i, (r, _, _, _) in enumerate(chunk)
                if r.prewarm
            )
            self.profiler.account(
                goodput=real - pre, prewarm=pre, pad_bucket=B * bucket - real
            )
        # one combined round trip (see _decode_once; the tunnel RTT floor
        # applies per fetch, not per byte)
        firsts, con_states = jax.device_get((firsts, con_states))
        self._finish_prefill_dispatch(chunk, firsts, con_states, full_lens)

    def _finish_prefill_dispatch(  # acp: megastep-seam — _save_prefix extracts KV
        self,
        chunk: list,
        firsts: np.ndarray,
        con_states: np.ndarray,
        full_lens: np.ndarray,
    ) -> None:
        """Host-side commit of one prefill dispatch's results (shared by
        the split _prefill_group and the megastep's fused final phase):
        snapshot prefixes, flip PREFILLING slots to decoding, stream first
        tokens + forced prefixes, and finish slots whose first token was
        terminal. ``self.cache`` must already hold the post-dispatch
        cache (prefix snapshots extract from it)."""
        # snapshot prefixes for future hits (engine thread; the state can't
        # change before decode extends past the cut). Hit slots save too:
        # their rows/tables now hold the FULL prompt KV, so the next turn can
        # reuse this whole context, not just the old prefix.
        if self._prefix_enabled:
            for req, slot, _, _m in chunk:
                if not req.truncated:
                    self._save_prefix(self._full_row(req), len(req.prompt), slot)
        self._state_dirty = True  # new slots: decode must re-upload state
        now = time.monotonic()
        for i, (req, slot, _, _m) in enumerate(chunk):
            s = req.sampling
            first_tok = int(firsts[i])
            self._con_states[slot] = int(con_states[i])
            self._constrained[slot] = bool(s.json_only)
            is_first = req.first_token_at == 0.0
            if is_first:
                req.first_token_at = now
                REGISTRY.observe(
                    "acp_engine_ttft_seconds", now - req.enqueued,
                    help="time to first token",
                )
            if not req.prewarm:
                # prefill complete: prompt KV resident, first token sampled.
                # For a resumed request this is also the end of its
                # preempt_stall window (phase attribution keys on it).
                self.flight.record(
                    "prefill_done", rid=req.rid, slot=slot,
                    seq=int(full_lens[i]), first=is_first,
                )
            prior = self._slots.get(slot)
            if prior is not None and prior.prefilling:
                # chunked prefill's FINAL chunk: the slot existed mid-prefill
                # (same request); it flips to decoding here, keeping its
                # admission stamp so victim-policy recency is admission
                # order, not final-chunk order
                self._prefilling_count -= 1
                admit_seq = prior.admit_seq
            else:
                self._admit_seq += 1
                admit_seq = self._admit_seq
            sl = _Slot(
                request=req,
                prompt_len=len(req.prompt),
                prefix_len=len(s.forced_prefix),
                first_token_at=req.first_token_at,
                admit_seq=admit_seq,
            )
            # active slots keep their prefill row too when the dedup
            # leader scan (its only consumer) is live: it compares token
            # prefixes against live slots on every admission, and
            # rebuilding prompt+prefix+resume per scan is O(slots x row)
            # on the engine thread. Gated so inert configs don't pin an
            # O(row) list per slot for nothing; the scan falls back to
            # _full_row for slots admitted while the knob was off.
            if self.prefix_dedup and self.kv_layout == "paged":
                sl.prefill_row = self._full_row(req)
            if self.spec_len:
                from .spec import SpecState

                sl.spec = SpecState(limit=self.spec_len)
            sl.generated.extend(s.forced_prefix)
            sl.generated.extend(req.resume_tokens)
            sl.generated.append(first_tok)
            if first_tok not in self.tokenizer.stop_tokens:
                # resumed requests already emitted prefix + resume tokens
                # before preemption — only the fresh token streams out
                self._stream(
                    req,
                    [first_tok] if req.resume_tokens
                    else list(s.forced_prefix) + [first_tok],
                )
            elif s.forced_prefix and not req.resume_tokens:
                self._stream(req, list(s.forced_prefix))
            self._slots[slot] = sl
            self._seq_lens[slot] = full_lens[i]  # cached prefix + suffix
            self._last_tokens[slot] = first_tok
            self._temps[slot] = s.temperature
            self._top_ks[slot] = s.top_k
            self._top_ps[slot] = s.top_p
            if (
                first_tok in self.tokenizer.stop_tokens
                or len(sl.generated) - sl.prefix_len >= s.max_tokens
            ):
                self._finish(
                    slot, "stop" if first_tok in self.tokenizer.stop_tokens else "length"
                )

    def _ensure_pages_for_block(self, need_tokens: Optional[dict] = None) -> None:
        """Paged mode: every active slot's table must cover the next K
        tokens before dispatch (or, per slot, ``need_tokens[slot]`` —
        the speculative verify path writes 1 + draft-length KV rows in one
        dispatch). A slot the pool can't cover triggers
        PREEMPT-AND-RESUME (never a silent truncation): prefix-cache
        entries yield first, then a policy victim is preempted — its
        generated-so-far tokens are saved on the request, its pages freed,
        and it is requeued at the FRONT of the admission queue to resume
        later via a prompt+partial prefill."""
        if self._faults.enabled:
            self._faults.apply_page_pressure(self._allocator)
        K = self.decode_block_size
        # Pass 1 — strict coverage: every slot gets exactly the pages this
        # block needs; lookahead can never starve a slot that strictly fits.
        crossed: list[int] = []
        for slot in list(self._slots):
            if slot not in self._slots:
                continue  # preempted as a victim for an earlier slot
            if self._slots[slot].parked or self._slots[slot].prefilling:
                # parked slots never decode; mid-prefill slots reserved
                # their whole row's pages at admission — neither needs
                # decode-block coverage
                continue
            need = K if need_tokens is None else need_tokens.get(slot, K)
            needed = -(-(int(self._seq_lens[slot]) + need) // self.page_size)
            # ctx edge: the decode block deactivates the slot on device at
            # max_ctx-1, so a fully-populated table is always enough — clamp
            # instead of force-finishing (a force-finish here could truncate
            # a json_only generation whose budget-aware closure planned on
            # the last few tokens before the edge)
            needed = min(needed, self.max_pages_per_seq)
            have = len(self._slot_pages.get(slot, []))
            if needed <= have:
                continue
            new_pages = self._alloc_with_preemption(needed - have, slot, need_tokens)
            if new_pages is None:
                continue  # slot itself was preempted (requeued or finished)
            self._append_pages(slot, new_pages)
            crossed.append(slot)
        # Pass 2 — opportunistic lookahead top-up, only for slots whose
        # table went dirty THIS round (their upload is already being paid):
        # with K == page_size a slot would otherwise cross a page boundary
        # on EVERY block, re-uploading the block table (one serialized
        # host->device RTT in the hot loop) per dispatch. Topping up to
        # `page_lookahead_blocks` blocks of pages makes it one upload per
        # lookahead window; a failed top-up is harmless.
        # speculation writes up to spec_len+1 rows per dispatch; size the
        # lookahead window to whichever dispatch shape is larger
        ahead = max(K, self.spec_len + 1) * self.page_lookahead_blocks
        for slot in crossed:
            if slot not in self._slot_pages:
                continue
            want = min(
                -(-(int(self._seq_lens[slot]) + ahead) // self.page_size),
                self.max_pages_per_seq,
            )
            have = len(self._slot_pages[slot])
            if want <= have:
                continue
            try:
                self._append_pages(slot, self._allocator.alloc(want - have))
            except MemoryError:
                pass  # pool tight: strict coverage already satisfied

    def _alloc_reclaiming_lookahead(
        self, n: int, requester: int, need_tokens: Optional[dict] = None
    ) -> list[int] | None:
        """Alloc ``n`` pages; on exhaustion, claw back other slots' UNUSED
        lookahead pages (beyond their strict next-block need) and retry.
        Without this, pass-2 top-ups from earlier rounds could hoard pages
        and preempt a strictly-fitting slot in a later round — 'lookahead
        never starves a strict fit' must hold across rounds, not just within
        one. The trimmed slots' tables re-upload next boundary crossing;
        that cost only occurs when the pool is already exhausted.

        ``need_tokens`` is THIS dispatch's per-slot row count (speculative
        verify writes 1 + draft rows, which can exceed the decode block):
        the reclaim floor must honor it, or a later slot's allocation in the
        same pass strips pages an earlier slot was just granted for its
        draft tail — the dispatch would then write that KV to the trash
        page while the host advances ``seq_len`` over it, and every later
        attention pass for the slot reads garbage."""
        try:
            return self._allocator.alloc(n)
        except MemoryError:
            pass
        K = self.decode_block_size
        reclaimed = False
        for slot in self._slots:
            table = self._slot_pages.get(slot)
            if slot == requester or not table:
                continue
            if self._slots[slot].parked:
                continue  # already trimmed to its park cut; nothing spare
            if self._slots[slot].prefilling:
                # a mid-prefill slot's "spare" pages are the reservation its
                # upcoming chunks write into — trimming them would tear the
                # admission-time all-pages-reserved invariant (the chunk
                # loop never allocates). Pressure takes the whole slot via
                # _pick_victim instead.
                continue
            need = K if need_tokens is None else max(K, need_tokens.get(slot, K))
            strict = min(
                -(-(int(self._seq_lens[slot]) + need) // self.page_size),
                self.max_pages_per_seq,
            )
            if len(table) > strict:
                excess = table[strict:]
                del table[strict:]
                self._block_tables[slot, strict : strict + len(excess)] = TRASH_PAGE
                self._allocator.free(excess)
                self._tables_dirty = True
                reclaimed = True
        if not reclaimed:
            return None
        try:
            return self._allocator.alloc(n)
        except MemoryError:
            return None

    def _alloc_with_preemption(
        self, n: int, requester: int, need_tokens: Optional[dict] = None
    ) -> list[int] | None:
        """Alloc ``n`` pages for an active slot, escalating on exhaustion:
        (1) claw back other slots' unused lookahead pages, (2) evict prefix
        -cache entries (cache must never starve live work), (3) preempt
        policy victims until the allocation fits or the requester itself is
        the victim. Returns None iff the requester was preempted."""
        while True:
            pages = self._alloc_reclaiming_lookahead(n, requester, need_tokens)
            if pages is not None:
                return pages
            if self._release_lru_parked():
                continue
            if self._evict_one_prefix_entry():
                continue
            victim = self._pick_victim()
            if victim is None:
                # no active slots left to yield (shouldn't happen — the
                # requester is active); preempt the requester defensively
                victim = requester
            self._preempt(victim)
            if victim == requester:
                return None

    def _pick_victim(self) -> Optional[int]:
        """Preemption victim policy (documented in docs/serving-engine.md):
        fewest sampled tokens first (least work lost / cheapest resume
        prefill), ties broken by MOST recently admitted (LIFO — the oldest
        requests keep their progress, mirroring the front-of-queue resume
        order so the engine converges instead of thrashing). Mid-prefill
        slots have sampled nothing, so they sort first among non-parked
        slots — preempting one loses only chunk compute, never tokens."""
        if not self._slots:
            return None
        # parked slots volunteer first (oldest park): their generation is
        # done and their caller already has its result — evicting one
        # costs at most a future suffix-prefill, never lost work
        parked = [(sl.parked_at, s) for s, sl in self._slots.items() if sl.parked]
        if parked:
            return min(parked)[1]
        return min(
            self._slots,
            key=lambda s: (
                len(self._slots[s].generated) - self._slots[s].prefix_len,
                -self._slots[s].admit_seq,
            ),
        )

    def _preempt(self, slot: int, reason: str = "pool_pressure") -> None:
        """Evacuate an active slot under pool pressure WITHOUT finishing
        it: save its sampled-so-far tokens and scheduling state on the
        request, free its pages, and requeue it at the front of the
        admission queue. On re-admission it prefills prompt+partial and
        decode continues — the caller's result is byte-identical (greedy)
        to an uncontended run, with only ``preempt_count`` as evidence."""
        if self._slots[slot].parked:
            # a parked slot has nothing to save or requeue — its future
            # resolved at park time; the "preemption" is a pure release
            self._release_parked(slot, reason=reason)
            return
        sl = self._slots.pop(slot)
        req = sl.request
        if sl.prefilling:
            # mid-prefill victim: no NEW sampled tokens to save — the
            # partial prompt KV is released with the pages and the request
            # re-enters the chunk loop from its (fresh) prefix-cache start
            # on re-admission. Byte-identical: nothing was sampled in THIS
            # admission. req.resume_tokens is left UNTOUCHED: a resumed
            # request preempted again mid-resume-prefill keeps its earlier
            # progress (its ``generated`` list is empty while prefilling —
            # overwriting from it here silently wiped the resume state and
            # re-streamed the whole generation after the second resume).
            self._prefilling_count -= 1
            self._unshare_followers(slot, sl)
        else:
            req.resume_tokens = list(sl.generated[sl.prefix_len:])
        # host KV tier: offload the written rows before the pages go —
        # re-admission then swaps them back instead of re-running prefill
        rows_written = sl.prefill_pos if sl.prefilling else int(self._seq_lens[slot])
        if not self._swap_out(slot, sl, reason="preempt") and not req.prewarm:
            # no host copy landed: the written KV is discarded and the
            # resume recomputes it — goodput retroactively becomes waste
            self.profiler.reclassify("preempt_discard", rows_written)
        req.preempt_count += 1
        self.preemptions += 1
        self._state_dirty = True
        self._seq_lens[slot] = 0
        self._last_tokens[slot] = 0
        self._con_states[slot] = 0
        self._constrained[slot] = False
        heapq.heappush(self._free, slot)
        if self.kv_layout == "paged":
            self._allocator.free(self._slot_pages.pop(slot, []))
            self._block_tables[slot, :] = TRASH_PAGE
            self._tables_dirty = True
        REGISTRY.counter_add(
            "acp_engine_preemptions_total", 1.0,
            help="slots preempted (and requeued) under KV pool pressure",
        )
        if not req.prewarm:
            # the victim + why: the decision the post-mortem always wants
            self.flight.record(
                "preempt", rid=req.rid, slot=slot, reason=reason,
                sampled=len(req.resume_tokens), count=req.preempt_count,
                mid_prefill=sl.prefilling,
            )
        # a request too big for the WHOLE pool can never be resumed — the
        # resume prefill itself would not fit. Finish honestly at current
        # length (this is real memory exhaustion, not contention; the old
        # force-finish behavior, now reserved for the impossible case).
        if self.kv_layout == "paged":
            K = self.decode_block_size
            ever_needed = min(
                -(-(len(self._full_row(req)) + K) // self.page_size),
                self.max_pages_per_seq,
            )
            if ever_needed > self._allocator.num_pages - 1:
                log.warning(
                    "rid %s needs %d pages to resume but the pool has %d; "
                    "finishing at current length", req.rid, ever_needed,
                    self._allocator.num_pages - 1,
                )
                self._resolve_preempted_as_length(req)
                return
        self._waiting.appendleft(req)
        log.info(
            "preempted rid %s (slot %d, %d tokens sampled, preempt #%d); "
            "requeued at front", req.rid, slot, len(req.resume_tokens),
            req.preempt_count,
        )

    def _resolve_preempted_as_length(self, req: _Request) -> None:
        """Terminal path for a preempted request that can never fit the
        pool again: resolve with what it generated (finish_reason length)."""
        gen = list(req.sampling.forced_prefix) + list(req.resume_tokens)
        if gen and gen[-1] in self.tokenizer.stop_tokens:
            gen = gen[:-1]
        now = time.monotonic()
        result = GenerationResult(
            text=self.tokenizer.decode(gen),
            tokens=gen,
            finish_reason="length",
            prompt_tokens=len(req.prompt),
            ttft_ms=(req.first_token_at - req.enqueued) * 1e3,
            latency_ms=(now - req.enqueued) * 1e3,
            preempt_count=req.preempt_count,
        )
        if not req.prewarm:
            self.flight.finish(
                req.rid, "length", trace=req.trace,
                tokens=len(gen), preempts=req.preempt_count,
            )
        if not req.future.done():
            req.future.set_result(result)
        REGISTRY.counter_add("acp_engine_requests_total", 1.0)
        REGISTRY.counter_add("acp_engine_tokens_total", float(len(gen)))

    def _append_pages(self, slot: int, new_pages: list[int]) -> None:
        table = self._slot_pages[slot]
        have = len(table)
        self._block_tables[slot, have : have + len(new_pages)] = new_pages
        table.extend(new_pages)
        self._tables_dirty = True

    def _ensure_dev_state(self) -> dict:
        """Device-resident decode state: the per-slot arrays (tokens,
        seq_lens, con_states, budgets, active, rng) round-trip through the
        decode block's carry and are fed back DONATED on the next block.
        Only a "dirty" block — admission, finish, cancel (anything that
        changed host-side slot assignment) — re-uploads the host mirrors.
        Through a high-RTT link (axon tunnel ~80ms/transfer) the old
        upload-8-arrays-every-block pattern cost ~10x the block compute;
        steady-state blocks now cost one dispatch + one result fetch.
        Shared by the split decode block and the megastep's fused decode
        phase (both must upload the same lanes). Paged block tables ride
        the same dirty discipline: re-uploaded only when a page was
        appended (or the state itself was re-uploaded), never per block."""
        if self._state_dirty or self._dev is None:
            # width bucketing: dispatch the smallest compiled width covering
            # the active slots (allocation is lowest-slot-first, so occupancy
            # stays compacted) — one live request doesn't pay max_slots of
            # compute. Width is recomputed only on dirty blocks; finishes
            # mark dirty, so the decay through narrower widths is preserved.
            max_active = max(
                s for s, sl in self._slots.items()
                if not sl.parked and not sl.prefilling
            ) + 1
            W = next(w for w in self.width_buckets if w >= max_active)
            active_mask = np.zeros(W, dtype=bool)
            for slot, sl in self._slots.items():
                if not sl.parked and not sl.prefilling and slot < W:
                    active_mask[slot] = True
            self._rng, step_rng = jax.random.split(self._rng)
            # once the token table exists it is passed unconditionally
            # (matching the prefill path): keying jit entries on "any slot
            # constrained" would DOUBLE the decode-width program matrix, and
            # the table is a device-resident array with no per-dispatch
            # transfer cost
            use_real = self._token_table is not None
            for slot, sl in self._slots.items():
                if not sl.parked and not sl.prefilling:
                    self._budgets[slot] = self._slot_budget(slot, sl)
            self._dev = {
                "W": W,
                "tokens": self._put(self._last_tokens[:W]),
                "seq_lens": self._put(self._seq_lens[:W]),
                "active": self._put(active_mask),
                "rng": step_rng,
                "temps": self._put(self._temps[:W]),
                "top_ks": self._put(self._top_ks[:W]),
                "top_ps": self._put(self._top_ps[:W]),
                "table": self._token_table if use_real else self._dummy_table,
                "con_states": self._put(self._con_states[:W]),
                "constrained": self._put(self._constrained[:W]),
                "min_close": self._min_close if use_real else self._dummy_min_close,
                "budgets": self._put(self._budgets[:W]),
            }
            self._state_dirty = False
        d = self._dev
        if self.kv_layout == "paged" and (
            self._tables_dirty or "block_tables" not in d
        ):
            d["block_tables"] = self._put(self._block_tables[: d["W"]])
            self._tables_dirty = False
            self.table_uploads += 1
        return d

    def _decode_once(self) -> None:  # acp: megastep-seam
        pending = self._fuse_pending
        self._fuse_pending = None
        self._apply_cancels()
        if not self._n_active():
            self._megastep_flush(pending)
            return
        if self._faults.enabled:
            spec = self._faults.pop("engine.force_preempt", steps=self.decode_steps)
            if spec is not None:
                victim = self._pick_victim()
                if victim is not None:
                    self._preempt(victim, reason="fault")
        if not self._n_active():
            self._megastep_flush(pending)
            return
        # speculative decoding: when enabled and at least one slot has a
        # draft, ONE verify dispatch replaces this iteration's decode block
        # (it commits 1 + accepted tokens per slot). When no slot drafts —
        # adversarial text, decayed adaptive caps — fall through to the
        # plain block path, which is exactly the spec-off engine. A fused
        # cycle's pending chunk lanes ride whichever dispatch wins.
        if self.spec_len and self._decode_spec(pending):
            return
        K = self.decode_block_size
        if self.kv_layout == "paged":
            self._ensure_pages_for_block()
            if not self._n_active():
                self._megastep_flush(pending)
                return
        d = self._ensure_dev_state()
        W = d["W"]
        n_act = self._n_active()
        KB = self.decode_block_size
        if pending is not None:
            out = self._megastep_dispatch(pending, d=d, n_act=n_act)
            if out is not None:
                return
            # fused shape over the program bound: dispatch the pending
            # chunk lanes through the split programs, then the plain block
            self._dispatch_pending_split(pending)
            if not self._n_active():
                self._publish_decode_gauges()
                return
            d = self._ensure_dev_state()  # finals may have joined
            W = d["W"]
            n_act = self._n_active()
        common = (
            d["tokens"], d["seq_lens"], d["active"], d["rng"],
            d["temps"], d["top_ks"], d["top_ps"], d["table"],
            d["con_states"], d["constrained"], d["min_close"], d["budgets"],
        )
        prof_t0 = self.profiler.start()
        if self.kv_layout == "paged":
            cache, tok_block, carry = self._jit_decode_paged(
                self.params, self.cache, *common, d["block_tables"]
            )
        else:
            cache, tok_block, carry = self._jit_decode(
                self.params, self.cache, *common
            )
        prog_key = (
            f"decode[{self.kv_layout},{W}x{KB}"
            f"{'+tbl' if d['table'] is not self._dummy_table else ''}]"
        )
        if self.profiler.enabled:
            # real/padded here are the DISPATCH-time view (lanes active as
            # uploaded); mid-block deactivations land precisely in the
            # account() call after the commit loop below
            self.profiler.record(
                prog_key, prof_t0,
                out=tok_block, real_tokens=n_act * KB,
                padded_tokens=(W - n_act) * KB,
                real_slots=n_act, padded_slots=W - n_act,
            )
        # ONE host round trip for both results — through a high-RTT link
        # sequential np.asarray fetches double the per-block latency floor.
        # con_states must stay mirrored so the next dirty upload (admission
        # into some other slot) doesn't clobber live automaton states.
        con_states, tok_block = jax.device_get((carry[2], tok_block))
        self.cache = cache
        self._commit_decode_block(tok_block, con_states, carry, d, prog_key)

    def _commit_decode_block(
        self,
        tok_block: np.ndarray,
        con_states: np.ndarray,
        carry: tuple,
        d: dict,
        prog_key: str,
    ) -> None:
        """Host-side commit of one decode-block dispatch (split or fused):
        re-seat the device-resident carry, mirror constraint states, commit
        each lane's tokens, and attribute the block's compute."""
        W = d["W"]
        d["tokens"], d["seq_lens"], d["con_states"], d["budgets"], d["active"], d["rng"] = carry
        self._con_states[:W] = con_states
        # tok_block: [K, W]
        K = tok_block.shape[0]
        self.decode_steps += K
        # one event per decode dispatch (batch-level, not per slot/token):
        # a timeline reader sees the cadence, not a flood
        self.flight.record(
            "decode_block", width=W, steps=K, active=self._n_active(),
            program=prog_key,
        )
        emitted = pre_emitted = 0
        for slot, sl in list(self._slots.items()):
            if sl.parked or sl.prefilling:
                continue  # parked/mid-prefill lanes were not in this dispatch
            if slot >= W:
                continue  # joined after the lanes were built (fused finals)
            n0 = len(sl.generated)
            self._consume_tokens(slot, sl, (int(tok_block[k, slot]) for k in range(K)))
            # sl stays valid after a _finish pops the slot — the delta is
            # this dispatch's committed tokens (stop tokens included: the
            # termination signal is useful compute)
            if sl.request.prewarm:
                pre_emitted += len(sl.generated) - n0
            else:
                emitted += len(sl.generated) - n0
        if self.profiler.enabled:
            # every one of the W*K computed positions lands in exactly one
            # cause: committed tokens are goodput (or prewarm), the rest —
            # inactive lanes and post-finish steps — is width padding
            self.profiler.account(
                goodput=emitted, prewarm=pre_emitted,
                pad_width=W * K - emitted - pre_emitted,
            )
        self._publish_decode_gauges()

    # -- fused megastep dispatch ------------------------------------------

    def _validate_pending(self, pending: dict) -> None:
        """Planning ran before this cycle's decode-site faults and page-
        pressure preemptions (the split path dispatches chunks first, so
        its preempts discard ALREADY-landed chunks; fusing inverts that
        order). Drop planned lanes whose slot was preempted, cancelled or
        expired since planning — dispatching them would write KV into
        freed (possibly reallocated) pages. Dropped lanes stay counted as
        budget spent (split parity: their dispatch would have landed
        before the preempt discarded it) but never reach the flight/
        counter record, which covers only real dispatches."""

        def live(c):
            slot, sl, st, _n = c
            return (
                self._slots.get(slot) is sl
                and sl.prefilling
                and sl.prefill_pos == st
                and sl.swap_entry is None
            )

        pending["mids"] = [c for c in pending["mids"] if live(c)]
        pending["finals"] = [c for c in pending["finals"] if live(c)]
        pending["plains"] = [c for c in pending["plains"] if live(c)]

        def live_swap(c):
            # a deferred staged restore stays valid only while the slot is
            # STILL mid-restore at the staged start (a preempt/cancel since
            # planning freed the pages the staged scatter would write)
            slot, sl, st, _n, _groups = c
            return (
                self._slots.get(slot) is sl
                and sl.prefilling
                and sl.prefill_pos == st
                and sl.swap_entry is not None
            )

        pending["swaps"] = [c for c in pending["swaps"] if live_swap(c)]

    def _dispatch_pending_split(self, pending: dict) -> None:
        """Fallback for a fused cycle that cannot (or should not) compile
        a new megastep shape: dispatch the planned lanes through the
        already-compiled split programs — staged restore scatters first
        (their rows are this cycle's oldest KV), then mid chunks, plain
        finals, and continuation finals — then record the round."""
        self._validate_pending(pending)
        mids, conts = pending["mids"], pending["finals"]
        plains, swaps = pending["plains"], pending["swaps"]
        with self._hol_clock():
            for slot, sl, st, n, groups in swaps:
                sl.swap_stall_s += self._commit_staged_swap(groups)
                self._advance_restore(slot, sl, st, n)
            for batch in _pow2_chunks(mids, self.prefill_batch_max):
                self._chunk_dispatch(batch)
            for batch in _pow2_chunks(plains, self.prefill_batch_max):
                self._prefill_group(self._chunk_items(batch))
            for batch in _pow2_chunks(conts, self.prefill_batch_max):
                self._prefill_group(
                    self._chunk_items(batch),
                    starts_np=np.asarray(
                        [st for _, _, st, _ in batch], dtype=np.int32
                    ),
                )
        for slot, sl, st, n in mids:
            sl.prefill_pos = st + n
            self._seq_lens[slot] = sl.prefill_pos
        self._record_chunk_round(
            pending["landed"] + [c[:4] for c in swaps] + mids + plains
            + conts, pending["spent"], pending["budget"],
            pending["restores"],
        )

    def _megastep_flush(self, pending: Optional[dict]) -> None:
        """Dispatch a fused cycle's pending chunk lanes when the cycle
        ended up with no decode/verify phase to fuse with (no active
        slots, or pressure preempted them all): a chunks-only megastep."""
        if pending is None:
            return
        if self._megastep_dispatch(pending) is None:
            self._dispatch_pending_split(pending)

    def _fuse_mid_lanes(self, batch: list) -> tuple:
        # acp: dispatch-lanes toks,lengths,starts,slots,page_ids,tables
        """Lane arrays for the megastep's mid-chunk phase: one batch,
        padded to a power of two (the split path's pow2 DECOMPOSITION has
        no padding rows; fusion trades those rows — accounted as pad_fuse
        waste — for dispatching once). Padding lanes write harmlessly:
        the slot layout clamps starts=max_ctx writes to the never-readable
        max_ctx-1 row (the spec-verify lane-default trick), paged routes
        every page write to TRASH_PAGE."""
        B = len(batch)
        Bp = 1 << (B - 1).bit_length()
        bucket = _next_bucket(max(n for _, _, _, n in batch), self.prefill_buckets)
        toks = np.zeros((Bp, bucket), dtype=np.int32)
        lengths = np.zeros(Bp, dtype=np.int32)
        starts = np.full(
            Bp, self.max_ctx if self.kv_layout == "slot" else 0, dtype=np.int32
        )
        slots = np.zeros(Bp, dtype=np.int32)
        for i, (slot, sl, st, n) in enumerate(batch):
            toks[i, :n] = sl.prefill_row[st : st + n]
            lengths[i] = n
            starts[i] = st
            slots[i] = slot
        if self.kv_layout == "paged":
            P = self.page_size
            page_ids = np.full((Bp, bucket // P), TRASH_PAGE, dtype=np.int32)
            for i, (slot, _sl, st, n) in enumerate(batch):
                # chunk boundaries are page-aligned (see _chunk_tokens), so
                # the commit's whole-page writes touch exactly this chunk's
                # fresh pages — never a page holding earlier KV
                sub = self._slot_pages[slot][st // P : -(-(st + n) // P)]
                page_ids[i, : len(sub)] = sub
            tables = np.full(
                (Bp, self.max_pages_per_seq), TRASH_PAGE, dtype=np.int32
            )
            tables[:B] = self._block_tables[[slot for slot, _, _, _ in batch]]
            lanes = (
                self._put(toks), self._put(lengths), self._put(starts),
                self._put(page_ids), self._put(tables),
            )
        else:
            lanes = (
                self._put(toks), self._put(lengths), self._put(starts),
                self._put(slots),
            )
        return lanes, bucket, Bp

    def _fuse_final_lanes(self, batch: list) -> tuple:
        """Lane arrays for the megastep's final-chunk phase: the shared
        _prefill_lanes builder (the budget seam must have one home) padded
        to a power-of-two batch. Padding lanes sample garbage that is
        never committed; their writes land on the trash page / clamped
        never-readable row exactly like _fuse_mid_lanes padding."""
        chunk = self._chunk_items(batch)
        starts = np.asarray([st for _, _, st, _ in batch], dtype=np.int32)
        ln = self._prefill_lanes(chunk, starts)
        B = len(batch)
        Bp = 1 << (B - 1).bit_length()
        bucket = ln["bucket"]

        def pad(a, fill):
            if Bp == B:
                return a
            out = np.full((Bp, *a.shape[1:]), fill, dtype=a.dtype)
            out[:B] = a
            return out

        pad_start = self.max_ctx if self.kv_layout == "slot" else 0
        self._rng, step_rng = jax.random.split(self._rng)
        sample = (
            step_rng,
            self._put(pad(ln["temps"], 0)),
            self._put(pad(ln["top_ks"], 0)),
            self._put(pad(ln["top_ps"], 1.0)),
            ln["table"],
            self._put(pad(ln["con_states0"], 0)),
            self._put(pad(ln["constrained0"], False)),
            ln["min_close"],
            self._put(pad(ln["budgets"], 1)),
        )
        toks_d = self._put(pad(ln["tokens"], 0))
        lens_d = self._put(pad(ln["lengths"], 0))
        starts_d = self._put(pad(starts, pad_start))
        if self.kv_layout == "paged":
            P = self.page_size
            page_ids = np.full((Bp, bucket // P), TRASH_PAGE, dtype=np.int32)
            for i, (slot, _sl, st, _n) in enumerate(batch):
                fresh = self._slot_pages[slot][st // P :]
                page_ids[i, : len(fresh)] = fresh
            tables = np.full(
                (Bp, self.max_pages_per_seq), TRASH_PAGE, dtype=np.int32
            )
            tables[:B] = self._block_tables[[slot for slot, _, _, _ in batch]]
            model_lanes = (
                toks_d, lens_d, starts_d, self._put(page_ids), self._put(tables)
            )
        else:
            model_lanes = (
                toks_d, lens_d, starts_d, self._put(pad(ln["slots"], 0))
            )
        return (model_lanes, sample), bucket, Bp, chunk, ln

    def _fuse_plain_lanes(self, batch: list) -> tuple:
        """Lane arrays for the megastep's plain-prefill phase (paged
        layout only): start-0 finals whose whole row fits one chunk run
        the plain causal program's raw body — byte-for-byte the
        chunked-off dispatch — padded to a power-of-two batch. Padding
        lanes sample garbage that is never committed and route every page
        write to TRASH_PAGE, exactly like _fuse_mid_lanes padding."""
        chunk = self._chunk_items(batch)
        starts = np.zeros(len(batch), dtype=np.int32)
        ln = self._prefill_lanes(chunk, starts)
        B = len(batch)
        Bp = 1 << (B - 1).bit_length()
        bucket = ln["bucket"]

        def pad(a, fill):
            if Bp == B:
                return a
            out = np.full((Bp, *a.shape[1:]), fill, dtype=a.dtype)
            out[:B] = a
            return out

        self._rng, step_rng = jax.random.split(self._rng)
        sample = (
            step_rng,
            self._put(pad(ln["temps"], 0)),
            self._put(pad(ln["top_ks"], 0)),
            self._put(pad(ln["top_ps"], 1.0)),
            ln["table"],
            self._put(pad(ln["con_states0"], 0)),
            self._put(pad(ln["constrained0"], False)),
            ln["min_close"],
            self._put(pad(ln["budgets"], 1)),
        )
        P = self.page_size
        page_ids = np.full((Bp, bucket // P), TRASH_PAGE, dtype=np.int32)
        for i, (_req, _slot, pages, _m) in enumerate(chunk):
            assert pages is not None
            page_ids[i, : len(pages)] = pages
        model_lanes = (
            self._put(pad(ln["tokens"], 0)),
            self._put(pad(ln["lengths"], 0)),
            self._put(page_ids),
        )
        return (model_lanes, sample), bucket, Bp, chunk, ln

    def _megastep_dispatch(  # acp: megastep-seam
        self,
        pending: dict,
        d: Optional[dict] = None,
        n_act: int = 0,
        ver: Optional[tuple] = None,
        ver_meta: Optional[dict] = None,
    ) -> Optional[bool]:
        """THE fused dispatch: one compiled program runs this cycle's
        pending staged swap-in scatters + mid chunks + plain finals +
        continuation finals + (decode block | spec verify). Returns True
        when it dispatched and committed; None when the caller must fall
        back to the split programs (a NEW fused shape past
        megastep_max_programs — fusion must not turn the jit cache into a
        combinatorial zoo, so rare shapes reuse the split programs that
        are already compiled)."""
        self._validate_pending(pending)
        mids, finals = pending["mids"], pending["finals"]
        plains, swaps = pending["plains"], pending["swaps"]
        if not mids and not finals and not plains:
            if not swaps and d is None and ver is None:
                # everything the cycle planned was invalidated pre-dispatch
                self._record_chunk_round(
                    pending["landed"], pending["spent"], pending["budget"],
                    pending["restores"],
                )
                return True
            if d is None and ver is None:
                # scatter-only cycle: nothing to fuse WITH — the split
                # commit is already a single dispatch, so a new fused
                # shape would buy nothing
                return None
            if not swaps:
                return None  # nothing to fuse; run the plain decode/verify
        # the shape key is host arithmetic — compute it and apply the
        # program bound BEFORE building/uploading any lane arrays, so a
        # fallback cycle never pays device transfers it throws away
        KB = self.decode_block_size
        mid_bucket = mid_Bp = fin_bucket = fin_Bp = pl_bucket = pl_Bp = 0
        if mids:
            mid_bucket = _next_bucket(
                max(n for _, _, _, n in mids), self.prefill_buckets
            )
            mid_Bp = 1 << (len(mids) - 1).bit_length()
        if plains:
            pl_bucket = max(
                _next_bucket(len(sl.prefill_row), self.prefill_buckets)
                for _slot, sl, _st, _n in plains
            )
            pl_Bp = 1 << (len(plains) - 1).bit_length()
        if finals:
            fin_bucket = max(
                _next_bucket(len(sl.prefill_row) - st, self.prefill_buckets)
                for _slot, sl, st, _n in finals
            )
            fin_Bp = 1 << (len(finals) - 1).bit_length()
        tbl = "+tbl" if self._token_table is not None else ""
        parts = []
        if swaps:
            # the scatter group sizes ARE the trace shape (one cache
            # scatter per pow2 group, in order)
            parts.append("s" + "-".join(
                str(int(ids.shape[0]))
                for _slot, _sl, _st, _n, groups in swaps
                for ids, _blocks in groups
            ))
        if mids:
            parts.append(f"m{mid_bucket}x{mid_Bp}")
        if plains:
            parts.append(f"p{pl_bucket}x{pl_Bp}")
        if finals:
            parts.append(f"f{fin_bucket}x{fin_Bp}")
        W = T = 0
        if d is not None:
            W = d["W"]
            parts.append(f"d{W}x{KB}")
        elif ver is not None:
            W, T = ver_meta["W"], ver_meta["T"]
            parts.append(f"v{W}x{T}")
        shape = (self.kv_layout, tuple(parts), tbl)
        if (
            shape not in self._megastep_shapes
            and len(self._megastep_shapes) >= self.megastep_max_programs
        ):
            self.megastep_fallbacks += 1
            REGISTRY.counter_add(
                "acp_engine_megastep_fallbacks_total", 1.0,
                help="fused cycles split-dispatched because a new megastep "
                "shape would exceed megastep_max_programs (the bound on "
                "distinct fused jit entries)",
            )
            return None
        mid_lanes = fin_lanes = pl_lanes = swap_arg = None
        fin_chunk = fin_ln = pl_chunk = pl_ln = None
        if swaps:
            swap_arg = tuple(
                (ids, blocks)
                for _slot, _sl, _st, _n, groups in swaps
                for ids, blocks in groups
            )
        if mids:
            mid_lanes, mid_bucket, mid_Bp = self._fuse_mid_lanes(mids)
        if plains:
            pl_lanes, pl_bucket, pl_Bp, pl_chunk, pl_ln = (
                self._fuse_plain_lanes(plains)
            )
        if finals:
            fin_lanes, fin_bucket, fin_Bp, fin_chunk, fin_ln = (
                self._fuse_final_lanes(finals)
            )
        dec_carry = dec_aux = None
        if d is not None:
            dec_carry = (
                d["tokens"], d["seq_lens"], d["con_states"], d["budgets"],
                d["active"], d["rng"],
            )
            extra = (d["block_tables"],) if self.kv_layout == "paged" else ()
            dec_aux = (
                d["temps"], d["top_ks"], d["top_ps"], d["table"],
                d["constrained"], d["min_close"], extra,
            )
        key = f"megastep[{self.kv_layout},{'+'.join(parts)}{tbl}]"
        new_shape = shape not in self._megastep_shapes
        self._megastep_shapes.add(shape)
        prof_t0 = self.profiler.start()
        cache, p_out, f_out, d_out, v_out = self._jit_megastep(
            self.params, self.cache, swap_arg, mid_lanes, pl_lanes,
            fin_lanes, dec_carry, dec_aux, ver,
        )
        self.megastep_dispatches += 1
        if new_shape:
            self.flight.record("megastep_shape", program=key)
        mid_real = sum(n for _, _, _, n in mids)
        pl_real = int(pl_ln["lengths"].sum()) if plains else 0
        fin_real = int(fin_ln["lengths"].sum()) if finals else 0
        swap_real = sum(
            int(ids.shape[0]) * self.page_size for ids, _ in (swap_arg or ())
        )
        if self.profiler.enabled:
            # swap rows count as real tokens only (the split scatter
            # records real_tokens with no goodput accounting; fused keeps
            # that) — restored rows are moved KV, not computed tokens
            real = mid_real + pl_real + fin_real + swap_real
            padded = 0
            if mids:
                padded += mid_Bp * mid_bucket - mid_real
            if plains:
                padded += pl_Bp * pl_bucket - pl_real
            if finals:
                padded += fin_Bp * fin_bucket - fin_real
            real_slots = len(mids) + len(plains) + len(finals)
            padded_slots = (
                (mid_Bp - len(mids)) + (pl_Bp - len(plains))
                + (fin_Bp - len(finals))
            )
            if d is not None:
                real += n_act * KB
                padded += (W - n_act) * KB
                real_slots += n_act
                padded_slots += W - n_act
            elif ver is not None:
                real += ver_meta["real_in"]
                padded += W * T - ver_meta["real_in"]
                real_slots += ver_meta["n_part"]
                padded_slots += W - ver_meta["n_part"]
            out_probe = (
                d_out[0] if d_out is not None
                else v_out[0] if v_out is not None
                else f_out[0] if f_out is not None
                else p_out[0] if p_out is not None
                else cache["k"]  # chunks-only: block on the committed KV
            )
            self.profiler.record(
                key, prof_t0, out=out_probe, real_tokens=real,
                padded_tokens=padded, real_slots=real_slots,
                padded_slots=padded_slots,
            )
            # the fused phases classify exactly as their split programs
            # would, plus pad_fuse for the pow2-padding rows fusion adds
            # (the split pow2 DECOMPOSITION has none)
            if mids:
                pre = sum(n for _, sl, _, n in mids if sl.request.prewarm)
                self.profiler.account(
                    goodput=mid_real - pre, prewarm=pre,
                    pad_bucket=len(mids) * mid_bucket - mid_real,
                    pad_fuse=(mid_Bp - len(mids)) * mid_bucket,
                )
            if plains:
                pre = sum(
                    int(pl_ln["lengths"][i])
                    for i, (r, _, _, _) in enumerate(pl_chunk)
                    if r.prewarm
                )
                self.profiler.account(
                    goodput=pl_real - pre, prewarm=pre,
                    pad_bucket=len(plains) * pl_bucket - pl_real,
                    pad_fuse=(pl_Bp - len(plains)) * pl_bucket,
                )
            if finals:
                pre = sum(
                    int(fin_ln["lengths"][i])
                    for i, (r, _, _, _) in enumerate(fin_chunk)
                    if r.prewarm
                )
                self.profiler.account(
                    goodput=fin_real - pre, prewarm=pre,
                    pad_bucket=len(finals) * fin_bucket - fin_real,
                    pad_fuse=(fin_Bp - len(finals)) * fin_bucket,
                )
        # ONE host round trip for every phase's results (None phases fetch
        # nothing — device_get maps over the pytree)
        carry = d_out[1] if d_out is not None else None
        f_np, p_np, dec_fetch, ver_np = jax.device_get((
            f_out,
            p_out,
            (carry[2], d_out[0]) if d_out is not None else None,
            v_out,
        ))
        self.cache = cache
        # commit order matters: swap bookkeeping and mid chunks advance
        # first (bookkeeping only — their cache writes already landed in
        # the program), then the decode/verify commit — its lanes predate
        # this cycle's finals, so it must run BEFORE finals/plains flip
        # their slots to ACTIVE (a freed-and-reused slot id would
        # otherwise read garbage lanes) — and the finals/plains commit
        # last. A fused restore adds NO stall seconds: the host->device
        # copy overlapped last cycle and the scatter rode this dispatch.
        for slot, sl, st, n, _groups in swaps:
            REGISTRY.counter_add(
                "acp_engine_kv_prefetch_commits_total", 1.0,
                help="host-KV restore chunks whose rows were prefetched "
                "(staged host->device a cycle early) and landed by scatter "
                "commit — the async-prefetch overlap win; chunks NOT "
                "counted here paid the blocking copy as host_stall",
            )
            self._advance_restore(slot, sl, st, n)
        for slot, sl, st, n in mids:
            sl.prefill_pos = st + n
            self._seq_lens[slot] = sl.prefill_pos
        if d_out is not None:
            con_states, tok_block = dec_fetch
            self._commit_decode_block(tok_block, con_states, carry, d, key)
        if v_out is not None:
            out_toks, n_emit, new_states = ver_np
            self._commit_spec_verify(
                out_toks, n_emit, new_states, ver_meta, key
            )
        if finals:
            firsts, fstates = f_np
            B = len(finals)
            self._finish_prefill_dispatch(
                fin_chunk, firsts[:B], fstates[:B], fin_ln["full_lens"]
            )
        if plains:
            p_firsts, p_states = p_np
            B = len(plains)
            self._finish_prefill_dispatch(
                pl_chunk, p_firsts[:B], p_states[:B], pl_ln["full_lens"]
            )
        self._record_chunk_round(
            pending["landed"] + [c[:4] for c in swaps] + mids + plains
            + finals, pending["spent"], pending["budget"],
            pending["restores"],
        )
        return True

    def _consume_tokens(self, slot: int, sl: _Slot, toks) -> None:
        """Host-side commit of one dispatch's newly sampled tokens for one
        slot (shared by the decode block and the speculative verify path):
        advance the host mirrors, stream to the caller, and finish at the
        first stop token / exhausted budget / context edge — the same spots
        the device deactivated the lane, so host and device bookkeeping
        never diverge."""
        s = sl.request.sampling
        done = None
        block_new: list[int] = []
        for tok in toks:
            self._seq_lens[slot] += 1
            self._last_tokens[slot] = tok
            sl.generated.append(tok)
            self.tokens_generated += 1
            if tok in self.tokenizer.stop_tokens:
                done = "stop"
                break
            block_new.append(tok)
            if (
                len(sl.generated) - sl.prefix_len >= s.max_tokens
                or self._seq_lens[slot] + 1 >= self.max_ctx
            ):
                done = "length"
                break
        self._stream(sl.request, block_new)
        if done is not None:
            self._finish(slot, done)

    def _stream(self, req: _Request, tokens: list[int]) -> None:
        """Engine-thread commit of newly sampled tokens to the caller:
        forwards the raw ids (on_tokens) and — when overlapped tool
        execution is on — detokenizes the delta and feeds the request's
        incremental tool parser, firing ``on_tool_call`` for every call
        whose braces closed in this commit. Shared by every path that
        emits tokens (prefill first-token + forced prefix, the plain
        decode block, and speculative multi-token commits), so early
        dispatch sees the same token stream in every engine mode."""
        req.emit(tokens)
        if req.tool_parser is None or not tokens:
            return
        req.detok_pending.extend(tokens)
        text = self.tokenizer.decode(req.detok_pending)
        if text.endswith("�"):
            return  # partial multi-byte char at a commit boundary; hold
        req.detok_pending.clear()
        self._feed_tool_parser(req, text)

    def _stream_flush(self, req: _Request) -> None:
        """Final flush at generation end: feed any held-back text (an
        incomplete UTF-8 tail never completed) so the parser has consumed
        exactly the generated text before the batch reconcile."""
        if req.tool_parser is None or not req.detok_pending:
            return
        text = self.tokenizer.decode(req.detok_pending)
        req.detok_pending.clear()
        self._feed_tool_parser(req, text)

    def _feed_tool_parser(self, req: _Request, text: str) -> None:
        try:
            calls = req.tool_parser.feed(text)
        except Exception:  # a broken parser must not kill the engine
            log.exception("tool stream parser failed; disabling for rid %s", req.rid)
            req.tool_parser = None
            return
        if not calls:
            return
        now = time.monotonic()
        for tc in calls:
            idx = len(req.early_calls)
            req.early_calls.append((now, tc))
            self.tool_calls_early += 1
            if not req.prewarm:
                # the emit edge of this call's tool_overlap_hidden window
                self.flight.record(
                    "tool_call", rid=req.rid, index=idx,
                    name=tc.function.name,
                )
            REGISTRY.counter_add(
                "acp_engine_tool_calls_early_total", 1.0,
                help="tool calls emitted from the decode stream before "
                "generation finished",
            )
            if req.on_tool_call is not None:
                try:
                    req.on_tool_call(idx, tc)
                except Exception:  # a broken consumer must not kill the engine
                    log.exception("on_tool_call failed; disabling for rid %s", req.rid)
                    req.on_tool_call = None

    def _publish_decode_gauges(self) -> None:
        REGISTRY.gauge_set(
            "acp_engine_active_slots", self._n_active(),
            help="occupied decode slots (parked slots excluded — see "
            "acp_engine_parked_slots)",
        )
        REGISTRY.gauge_set(
            "acp_engine_waiting_requests", len(self._waiting),
            help="admission queue depth",
        )
        REGISTRY.gauge_set(
            "acp_engine_preempted_waiting",
            self._preempted_waiting(),
            help="preempted requests requeued and awaiting resume",
        )
        REGISTRY.gauge_set(
            "acp_engine_prefilling_slots",
            float(self._prefilling_count),
            help="slots admitted but still mid-prefill under the chunked "
            "token-budget scheduler",
        )

    def _slot_budget(self, slot: int, sl: _Slot) -> int:  # acp: budget-seam
        """Sampled tokens this slot may still emit — min of its remaining
        ``max_tokens`` and the context edge (the device deactivates a slot
        after the token that lands it at max_ctx-1). The decode block and
        the speculative verify dispatch MUST share this computation: the
        device-side budget decrement and host max_tokens accounting stay
        consistent only if both paths upload the same number."""
        token_left = sl.request.sampling.max_tokens - (
            len(sl.generated) - sl.prefix_len
        )
        ctx_left = self.max_ctx - 1 - int(self._seq_lens[slot])
        return max(0, min(token_left, ctx_left))

    def _slot_ctx(self, sl: _Slot) -> np.ndarray:
        """Prompt+generated as one int32 view for the drafter, synced by
        appending only the tokens emitted since the last dispatch."""
        n_prompt = len(sl.request.prompt)
        total = n_prompt + len(sl.generated)
        if sl.ctx_buf is None:
            sl.ctx_buf = np.empty(max(total, self.max_ctx), dtype=np.int32)
            sl.ctx_buf[:n_prompt] = sl.request.prompt
            sl.ctx_len = n_prompt
        elif total > sl.ctx_buf.shape[0]:
            sl.ctx_buf = np.concatenate(
                [sl.ctx_buf, np.empty(total, dtype=np.int32)]
            )
        if sl.ctx_len < total:
            sl.ctx_buf[sl.ctx_len : total] = sl.generated[sl.ctx_len - n_prompt :]
            sl.ctx_len = total
        return sl.ctx_buf[:total]

    def _decode_spec(self, pending: Optional[dict] = None) -> bool:  # acp: megastep-seam
        # acp: dispatch-lanes inputs,n_input,starts,active,budgets,proposed
        """One speculative decode iteration: draft host-side (n-gram prompt
        lookup over prompt + generated-so-far), verify every position in a
        single batched dispatch, commit the accepted prefix + one corrected
        token per slot. Returns False (nothing dispatched) when no active
        slot produced a draft — the caller then runs the plain decode block,
        which is byte-for-byte today's non-speculative path.

        Composition notes:
        - KV: the verify program writes every draft position optimistically;
          rollback of a rejected tail is implicit — the host advances
          ``seq_lens`` only over emitted tokens and attention never reads
          beyond ``seq_len`` (paged: the extra rows sit in pages the slot
          already owns, exactly like decode-block lookahead pages).
        - Device-resident decode state: the spec path syncs with the host
          every dispatch by construction (the drafter needs the sampled
          tokens), so it re-uploads the small per-slot arrays each time and
          marks ``_state_dirty`` — a later fallback block re-uploads the
          carried state like any other dirty block.
        - Preemption/prefix cache: drafts are host-only; page pressure in
          ``_ensure_pages_for_block`` preempts exactly as in the block path
          (preempted slots are dropped from this dispatch).
        """
        from .spec import ngram_propose

        T = self.spec_len + 1  # one trace shape per width bucket
        drafts: dict[int, list[int]] = {}
        budgets_eff: dict[int, int] = {}
        any_draft = False
        for slot, sl in self._slots.items():
            if sl.parked or sl.prefilling:
                continue
            budget = self._slot_budget(slot, sl)
            budgets_eff[slot] = budget
            # the dispatch emits up to draft+1 tokens and writes draft+1 KV
            # rows: cap the draft so both stay within budget (and therefore
            # within the context edge — budget <= ctx_left)
            cap = min(sl.spec.cap(), budget - 1) if sl.spec else 0
            d: list[int] = []
            if cap > 0:
                d = ngram_propose(self._slot_ctx(sl), self.spec_ngram, cap)
            drafts[slot] = d
            any_draft = any_draft or bool(d)
        if not any_draft:
            return False
        if self.kv_layout == "paged":
            # page coverage for the widest row each slot verifies; a slot
            # preempted under pressure here simply leaves the dispatch
            self._ensure_pages_for_block(
                {slot: 1 + len(d) for slot, d in drafts.items()}
            )
            if not self._n_active():
                self._megastep_flush(pending)
                return True
            drafts = {s: d for s, d in drafts.items() if s in self._slots}
            if not any(drafts.values()):
                return False  # the drafted slots were preempted; block-decode
        force_reject = bool(
            self._faults.enabled
            and self._faults.pop("engine.spec_mismatch") is not None
        )
        W = next(
            w for w in self.width_buckets
            if w >= max(
                s for s, sl in self._slots.items()
                if not sl.parked and not sl.prefilling
            ) + 1
        )
        inputs = np.zeros((W, T), dtype=np.int32)
        # lanes NOT in this dispatch (free, parked, mid-prefill) must write
        # their optimistic K/V somewhere HARMLESS: n_input=0 sends every
        # paged write to the trash page (token_write_targets masks by
        # length), and starts=max_ctx clamps the slot layout's scatter to
        # row max_ctx-1, which attention can never read (a lane deactivates
        # at max_ctx-1). The old defaults (n_input=1, starts=0) scattered
        # one garbage row into position 0 of the lane's LIVE KV — harmless
        # for free lanes (the next prefill overwrites from 0) but corrupting
        # for parked prompt KV awaiting adoption and for mid-prefill slots.
        n_input = np.zeros(W, dtype=np.int32)
        starts = np.full(W, self.max_ctx, dtype=np.int32)
        active = np.zeros(W, dtype=bool)
        budgets = np.zeros(W, dtype=np.int32)
        proposed = np.zeros(W, dtype=np.int32)
        for slot, sl in self._slots.items():
            if sl.parked or sl.prefilling:
                continue
            d = drafts.get(slot, [])
            inputs[slot, 0] = self._last_tokens[slot]
            if d:
                inputs[slot, 1 : 1 + len(d)] = d
            n_input[slot] = 1 + len(d)
            starts[slot] = self._seq_lens[slot]
            active[slot] = True
            budgets[slot] = budgets_eff[slot]
            proposed[slot] = len(d)
        use_real = self._token_table is not None
        self._rng, step_rng = jax.random.split(self._rng)
        args = [
            self.params,
            self.cache,
            self._put(inputs),
            self._put(n_input),
            self._put(starts),
            self._put(active),
            step_rng,
            self._put(self._temps[:W]),
            self._put(self._top_ks[:W]),
            self._put(self._top_ps[:W]),
            self._token_table if use_real else self._dummy_table,
            self._put(self._con_states[:W]),
            self._put(self._constrained[:W]),
            self._min_close if use_real else self._dummy_min_close,
            self._put(budgets),
            self._put(np.asarray(force_reject)),
        ]
        if self.kv_layout == "paged":
            args.append(self._put(self._block_tables[:W]))
        ver_meta = {
            "W": W, "T": T, "drafts": drafts, "proposed": proposed,
            "force_reject": force_reject, "real_in": int(n_input.sum()),
            "n_part": int(active.sum()),
        }
        if pending is not None:
            # fused cycle: the verify pass rides the megastep with the
            # pending chunk lanes (one dispatch). Shape-bound fallback
            # split-dispatches the chunks, then verifies standalone below
            # (finals activated by the fallback join the NEXT cycle's
            # lanes — per-request greedy bytes are unaffected).
            if self._megastep_dispatch(
                pending, ver=tuple(args[2:]), ver_meta=ver_meta
            ):
                return True
            self._dispatch_pending_split(pending)
            # the fallback's chunk dispatches DONATED the cache args[1]
            # captured above and reassigned self.cache — verifying against
            # the stale buffer would crash (deleted buffer) or silently
            # discard this cycle's chunk KV writes
            args[1] = self.cache
        prof_t0 = self.profiler.start()
        cache, out_toks, n_emit, new_states = self._jit_verify(*args)
        self.cache = cache
        spec_prog_key = (
            f"spec_verify[{self.kv_layout},{W}x{T}{'+tbl' if use_real else ''}]"
        )
        if self.profiler.enabled:
            n_part = ver_meta["n_part"]
            real_in = ver_meta["real_in"]
            self.profiler.record(
                spec_prog_key, prof_t0,
                out=out_toks, real_tokens=real_in,
                padded_tokens=W * T - real_in,
                real_slots=n_part, padded_slots=W - n_part,
            )
        # one combined host round trip, same discipline as the block path
        out_toks, n_emit, new_states = jax.device_get((out_toks, n_emit, new_states))
        self._commit_spec_verify(
            out_toks, n_emit, new_states, ver_meta, spec_prog_key
        )
        return True

    def _commit_spec_verify(
        self,
        out_toks: np.ndarray,
        n_emit: np.ndarray,
        new_states: np.ndarray,
        ver_meta: dict,
        prog_key: str,
    ) -> None:
        """Host-side commit of one speculative-verify dispatch (split or
        fused): mirror constraint states, commit accepted prefixes + the
        corrected token per slot, feed the AIMD controllers, and attribute
        the pass's compute."""
        W, T = ver_meta["W"], ver_meta["T"]
        drafts = ver_meta["drafts"]
        proposed = ver_meta["proposed"]
        force_reject = ver_meta["force_reject"]
        self._con_states[:W] = new_states
        self.decode_steps += 1  # one model forward, however many tokens land
        self.spec_dispatches += 1
        self._state_dirty = True  # host mirrors advanced; next block re-uploads
        sp_emitted = sp_pre = sp_rejected = 0
        for slot, sl in list(self._slots.items()):
            if sl.parked or sl.prefilling or slot >= W:
                continue
            n = int(n_emit[slot])
            prop = int(proposed[slot])
            n_gen0 = len(sl.generated)
            if prop:
                # emitted = accepted prefix + one corrected token — except
                # when emission ended ON a matching draft token (stop token
                # or budget exhaustion), where the final token is an
                # accepted draft token too. force_reject means the device
                # treated every position as mismatched; a numerically-equal
                # final token must not count as accepted or the AIMD
                # controller would see partial acceptance under the
                # spec_mismatch fault and never decay.
                d = drafts.get(slot, [])
                acc = max(0, n - 1)
                if (
                    not force_reject
                    and 0 < n <= len(d)
                    and int(out_toks[slot, n - 1]) == d[n - 1]
                ):
                    acc = n
                acc = min(acc, prop)
                self.spec_proposed += prop
                self.spec_accepted += acc
                if sl.spec is not None:
                    sl.spec.observe(prop, acc)
                REGISTRY.counter_add(
                    "acp_engine_spec_proposed_total", float(prop),
                    help="draft tokens proposed to speculative verification",
                )
                REGISTRY.counter_add(
                    "acp_engine_spec_accepted_total", float(acc),
                    help="draft tokens accepted by speculative verification",
                )
            if n > 0:
                self._consume_tokens(slot, sl, (int(t) for t in out_toks[slot, :n]))
            d_tok = len(sl.generated) - n_gen0
            if sl.request.prewarm:
                sp_pre += d_tok
            else:
                sp_emitted += d_tok
            if prop:
                # positions the verify pass computed past the emitted
                # prefix: rejected draft tail (the speculation gamble lost)
                sp_rejected += max(0, 1 + prop - n)
        if self.profiler.enabled:
            self.profiler.account(
                goodput=sp_emitted, prewarm=sp_pre,
                spec_rejected=sp_rejected,
                pad_width=W * T - sp_emitted - sp_pre - sp_rejected,
            )
        if self.flight.enabled:
            # one aggregate event per verify dispatch: the propose/verify/
            # accept decision, with how much the drafts actually paid
            self.flight.record(
                "spec_verify",
                slots=int(sum(1 for d in drafts.values() if d)),
                proposed=int(sum(len(d) for d in drafts.values())),
                emitted=int(sum(int(n_emit[s]) for s in drafts)),
                forced_reject=force_reject,
                program=prog_key,
            )
        self._publish_decode_gauges()

    def _finish(self, slot: int, reason: str) -> None:
        sl = self._slots.get(slot)
        if sl is None:
            return
        if sl.parked:
            # the future resolved when the slot parked; a finish now is a
            # cancel/stop/drain — release the lingering bookkeeping
            self._release_parked(slot, reason=reason)
            return
        if sl.prefilling:
            # a finish can only reach a mid-prefill slot via cancel, a
            # replicated deadline release, or shutdown drain — nothing was
            # sampled, so there is no result to resolve: release the
            # partial KV and fail like a never-admitted request
            self._drop_prefilling_slot(slot)
            req = sl.request
            self._cancelled.discard(req.rid)
            self._applied_cancels.discard(req.rid)
            if not req.prewarm:
                self.flight.record(
                    "cancel", rid=req.rid, slot=slot, where="mid_prefill",
                    reason=reason,
                )
                self.flight.discard(req.rid)
            if not req.future.done():
                if reason == "cancelled":
                    req.future.cancel()
                else:
                    req.future.set_exception(RuntimeError("engine stopped"))
            return
        req = sl.request
        if reason in ("stop", "length"):
            # a cancelled/drained request must not fire late tool events —
            # its caller is gone and an early CR would be pure orphan
            self._stream_flush(req)
        if req.early_calls:
            # overlap window this turn made available: time between each
            # call becoming dispatchable and the generation completing
            now = time.monotonic()
            saved = sum(now - t for t, _ in req.early_calls)
            self.tool_overlap_saved_s += saved
            REGISTRY.counter_add(
                "acp_engine_tool_overlap_saved_seconds", saved,
                help="per early tool call, seconds between its dispatch "
                "becoming possible and its turn's generation finishing",
            )
        if (
            req.park
            and reason in ("stop", "length")
            and not self._stopping
            and self._park_cut_for(sl) > 0
        ):
            self._park(slot, sl, reason)
            return
        kv_entry = None
        if req.export_kv and reason in ("stop", "length") and not self._stopping:
            # disaggregation: extract the prompt KV BEFORE the slot (and in
            # paged mode its pages) is torn down below
            kv_entry = self._export_kv_handoff(slot, sl)
        self._slots.pop(slot)
        self._state_dirty = True  # device lane must be re-uploaded inactive
        self._cancelled.discard(req.rid)
        self._applied_cancels.discard(req.rid)
        self._seq_lens[slot] = 0
        self._last_tokens[slot] = 0
        self._con_states[slot] = 0
        self._constrained[slot] = False
        heapq.heappush(self._free, slot)
        if self.kv_layout == "paged":
            self._allocator.free(self._slot_pages.pop(slot, []))
            self._block_tables[slot, :] = TRASH_PAGE
        self._resolve_result(sl, reason, slot=slot, kv_entry=kv_entry)

    def _resolve_result(
        self, sl: _Slot, reason: str, slot: int = -1, kv_entry=None
    ) -> None:
        """Resolve a slot's future with its GenerationResult — shared by the
        normal finish and the park transition (a parked slot's caller gets
        its result immediately; only the KV bookkeeping lingers)."""
        gen = sl.generated
        if gen and gen[-1] in self.tokenizer.stop_tokens:
            gen = gen[:-1]
        now = time.monotonic()
        result = GenerationResult(
            text=self.tokenizer.decode(gen),
            tokens=gen,
            finish_reason=reason,
            prompt_tokens=sl.prompt_len,
            ttft_ms=(sl.first_token_at - sl.request.enqueued) * 1e3,
            latency_ms=(now - sl.request.enqueued) * 1e3,
            preempt_count=sl.request.preempt_count,
            kv_handoff=kv_entry,
        )
        if not sl.request.prewarm:
            # terminal flight event + phase attribution export (histograms
            # and, when the request carried a trace context, OTLP child
            # spans under the Task's trace). BEFORE the future resolves, so
            # a caller that immediately queries /timeline sees a complete
            # record instead of racing the engine thread.
            self.flight.finish(
                sl.request.rid, reason, slot=slot, trace=sl.request.trace,
                tokens=len(gen), preempts=sl.request.preempt_count,
            )
        if not sl.request.future.done():
            sl.request.future.set_result(result)
        REGISTRY.counter_add("acp_engine_requests_total", 1.0)
        REGISTRY.counter_add("acp_engine_tokens_total", float(len(gen)))

    # -- parked slots (overlapped tool execution) -------------------------

    def _park_cut_for(self, sl: _Slot) -> int:
        """KV rows a parked slot can lend the conversation's next turn:
        the PROMPT rows only (the next turn re-renders the assistant
        message, so generated-token KV can never match), page-aligned in
        paged mode because continuation prefill resumes at page grain."""
        if self.kv_layout == "paged":
            return (sl.prompt_len // self.page_size) * self.page_size
        return sl.prompt_len

    def _park(self, slot: int, sl: _Slot, reason: str) -> None:
        """Voluntary park at generation end (the preempt machinery's page
        discipline, minus the victim scan and the requeue): the caller's
        future resolves NOW with the finished result; the slot stays
        occupied holding only its prompt KV — surplus pages are freed —
        so the next turn of this conversation prefills just its suffix.
        Under pool pressure parked slots are the first to yield
        (_release_parked), and an unclaimed park expires after
        park_max_s."""
        req = sl.request
        self._state_dirty = True
        self._cancelled.discard(req.rid)
        self._applied_cancels.discard(req.rid)
        cut = self._park_cut_for(sl)
        sl.parked = True
        sl.parked_at = time.monotonic()
        sl.park_cut = cut
        self._parked_count += 1
        # host mirrors: the lane is finished on device (never advances);
        # seq_len records the rows that remain meaningful for adoption
        self._seq_lens[slot] = cut
        self._last_tokens[slot] = 0
        self._con_states[slot] = 0
        self._constrained[slot] = False
        self._budgets[slot] = 0
        if self.kv_layout == "paged":
            keep = cut // self.page_size
            table = self._slot_pages.get(slot, [])
            if len(table) > keep:
                excess = table[keep:]
                del table[keep:]
                self._block_tables[slot, keep : keep + len(excess)] = TRASH_PAGE
                self._allocator.free(excess)
                self._tables_dirty = True
        self.parks += 1
        REGISTRY.counter_add(
            "acp_engine_parks_total", 1.0,
            help="slots parked at generation end awaiting the "
            "conversation's next turn",
        )
        if not req.prewarm:
            self.flight.record("park", rid=req.rid, slot=slot, cut=cut)
        self._publish_park_gauge()
        self._resolve_result(sl, reason, slot=slot)

    def _release_parked(self, slot: int, reason: str = "pressure") -> None:
        """Free a parked slot entirely (pressure, expiry, stop, or a
        forced preemption landing on it). The future resolved at park
        time, so this is pure bookkeeping — the voluntary, no-victim-scan
        analogue of _preempt's page release."""
        sl = self._slots.get(slot)
        if sl is None or not sl.parked:
            return
        if reason in ("pressure", "expired", "pool_pressure", "fault"):
            # the prompt KV is still reusable (same persona/conversation
            # re-arriving later): offload it before the pages go, so the
            # host tier's prefix match can restore instead of re-prefilling
            self._swap_out(slot, sl, reason=f"park_{reason}")
        if not sl.request.prewarm:
            self.flight.record(
                "park_release", rid=sl.request.rid, slot=slot, reason=reason
            )
            # the rid's timeline was retired when the park resolved its
            # future — retire the release event too (extends the finished
            # timeline) instead of leaving an orphan live entry
            self.flight.discard(sl.request.rid)
        self._slots.pop(slot)
        self._parked_count -= 1
        self._state_dirty = True
        self._seq_lens[slot] = 0
        self._last_tokens[slot] = 0
        heapq.heappush(self._free, slot)
        if self.kv_layout == "paged":
            self._allocator.free(self._slot_pages.pop(slot, []))
            self._block_tables[slot, :] = TRASH_PAGE
            self._tables_dirty = True
        self.park_releases += 1
        self._publish_park_gauge()

    def _release_lru_parked(self, exclude: Optional[int] = None) -> bool:
        """Release the longest-parked slot (if any). True if one yielded."""
        parked = [
            (sl.parked_at, s)
            for s, sl in self._slots.items()
            if sl.parked and s != exclude
        ]
        if not parked:
            return False
        self._release_parked(min(parked)[1])
        return True

    def _sweep_parked(self) -> None:
        """Expire parked slots whose next turn never came (final answers,
        failed tasks). Engine-thread, every loop iteration — cheap."""
        if not self.park_max_s:
            return
        now = time.monotonic()
        expired = [
            s for s, sl in self._slots.items()
            # wall-clock expiry is safe here WITHOUT the leader seam: the
            # constructor forces park_max_s=0 under coordination (parking
            # disabled entirely), so this compare never runs in lockstep
            if sl.parked and now - sl.parked_at > self.park_max_s  # acp-lint: disable=coord-wallclock
        ]
        for slot in expired:
            self._release_parked(slot, reason="expired")

    def _match_parked(self, req: _Request) -> Optional[int]:
        """Parked slot whose prompt KV covers the longest prefix of this
        request's row — the adoption candidate for a conversation's next
        turn. Strict prefix (suffix tokens must remain to prefill)."""
        if req.truncated:
            return None
        full = self._full_row(req)
        best, best_cut = None, 0
        for slot, sl in self._slots.items():
            if not sl.parked:
                continue
            cut = sl.park_cut
            if (
                0 < cut < len(full)
                and cut > best_cut
                and list(sl.request.prompt[:cut]) == full[:cut]
            ):
                best, best_cut = slot, cut
        return best

    def _reject_oversize_head(self, req: _Request, total_pages: int) -> bool:
        """Paged admission guard shared by the free-slot and parked-
        adoption paths: a row bigger than the ENTIRE pool can never fit —
        fail it up front (waiting would spin forever). True if rejected."""
        if total_pages <= self._allocator.num_pages - 1:
            return False
        self._waiting.popleft()
        if not req.prewarm:
            self.flight.record(
                "cancel", rid=req.rid, where="oversize",
                pages_needed=total_pages,
            )
            self.flight.discard(req.rid)
        req.future.set_exception(
            RuntimeError(
                f"prompt needs {total_pages} KV pages but the pool has "
                f"{self._allocator.num_pages - 1}"
            )
        )
        return True

    def _adopt_parked(self, req: _Request, slot: int) -> Optional[list]:
        """Hand a parked slot to the next turn of its conversation (the
        head of the waiting deque). Returns ``[group_item]`` on success,
        ``[]`` when the head was popped and failed (oversize prompt), or
        ``None`` when pages ran short even after yielding — the caller
        breaks and the head waits, with the parked slot intact (FIFO)."""
        cut = self._slots[slot].park_cut
        pages: Optional[list[int]] = None
        if self.kv_layout == "paged":
            total_pages = -(-len(self._full_row(req)) // self.page_size)
            if self._reject_oversize_head(req, total_pages):
                return []
            kept = list(self._slot_pages.get(slot, []))
            fresh: Optional[list[int]] = None
            while fresh is None:
                try:
                    fresh = self._allocator.alloc(total_pages - len(kept))
                except MemoryError:
                    # OTHER parked slots and cache entries yield before the
                    # adoption fails; never release the adoptee itself
                    if self._release_lru_parked(exclude=slot):
                        continue
                    if not self._evict_one_prefix_entry():
                        break
            if fresh is None:
                return None
            pages = kept + fresh
            # keep _slot_pages coherent IMMEDIATELY (the block-table
            # install in _fill_slots re-writes it identically later): a
            # dedup follower in this same admission group may pick the
            # adopter as its leader, and reading the parked slot's stale
            # kept-only list here would truncate its share — rows between
            # the park cut and the share cut would map to never-written
            # follower pages and decode over garbage KV
            self._slot_pages[slot] = list(pages)
        self._slots.pop(slot)  # the new turn takes the slot over in place
        self._parked_count -= 1
        self.park_adoptions += 1
        if not req.prewarm:
            self.flight.record("adopt", rid=req.rid, slot=slot, cut=cut)
        REGISTRY.counter_add(
            "acp_engine_park_adoptions_total", 1.0,
            help="parked slots adopted by their conversation's next turn "
            "(suffix-only prefill)",
        )
        self._publish_park_gauge()
        self._waiting.popleft()
        return [(req, slot, pages, (None, {"cut": cut, "in_slot": True}))]

    def _n_active(self) -> int:  # acp: cross-thread
        """Slots actively DECODING — parked slots linger without work and
        mid-prefill slots haven't sampled yet (see _has_work for the
        loop-level any-work predicate)."""
        return len(self._slots) - self._parked_count - self._prefilling_count

    def _has_parked(self) -> bool:
        return self._parked_count > 0

    def _publish_park_gauge(self) -> None:
        REGISTRY.gauge_set(
            "acp_engine_parked_slots",
            float(self._parked_count),
            help="slots parked at generation end, prompt KV resident, "
            "awaiting the conversation's next turn",
        )

    # -- KV memory tiers: host-RAM offload + shared-prefix dedup ----------

    def set_host_kv_bytes(self, n: int) -> None:
        """Resize (0 = disable) the host KV tier. Idle-engine callers only
        (benches/tests A/B the knob on one warmed engine); shrinking LRU-
        evicts entries beyond the new budget."""
        from ..ops.paged import HostKVPool

        self.host_kv_bytes = max(0, int(n))
        if not self.host_kv_bytes:
            self._host_pool = None
        elif self._host_pool is None:
            self._host_pool = HostKVPool(self.host_kv_bytes)
        else:
            pool = self._host_pool
            pool.max_bytes = self.host_kv_bytes
            while pool.used_bytes > pool.max_bytes and len(pool):
                pool.pop(next(iter(pool._entries)))
        self._publish_memory_state()

    def inject_host_kv(self, entry) -> bool:
        """Land a :class:`HostKVEntry` in this engine's host-KV tier
        (thread-safe; the fleet router's prefill→decode handoff path).
        The entry is enqueued here and committed to the pool by the engine
        thread at the top of ``_fill_slots`` — BEFORE admission matching —
        so inject-then-submit ordering guarantees a subsequently submitted
        request sees it in ``_collect_group``'s host-tier prefix match.
        Returns False (caller falls back to a full prefill) when the host
        tier is disabled or the engine isn't running."""
        if self._host_pool is None or self._thread is None or self._stopping:
            return False
        self._kv_inject.put(entry)
        return True

    def _drain_kv_inject(self) -> None:
        """Commit injected handoff entries to the host pool (engine
        thread; called from _fill_slots before admission matching)."""
        landed = False
        while True:
            try:
                entry = self._kv_inject.get_nowait()
            except queue.Empty:
                break
            pool = self._host_pool
            if pool is not None and pool.put(entry):
                landed = True
                self.kv_injects += 1
                self.flight.record(
                    "kv_inject", rid=entry.rid, tokens=entry.cut,
                    bytes=entry.nbytes,
                )
            # a refused entry (pool shrunk below its size) just drops:
            # the request it fed recomputes its prefill, byte-identically
        if landed:
            self._publish_memory_state()

    def _export_kv_handoff(self, slot: int, sl: _Slot):  # acp: kv-seam
        """Extract a finishing export_kv request's prompt KV into a
        :class:`HostKVEntry` (the disaggregation handoff unit) — the same
        page-aligned rows-[0, cut) extraction ``_swap_out`` performs, but
        attached to the result instead of this engine's own pool. Returns
        None (caller degrades to no handoff) for truncated prompts, dedup
        followers, or too few written rows."""
        req = sl.request
        if req.truncated or sl.share_of is not None:
            return None
        rows = int(self._seq_lens[slot])
        row = self._full_row(req)
        cut = min(rows, len(row) - 1)  # strict prefix: decode must model >= 1
        if self.kv_layout == "paged":
            cut = (cut // self.page_size) * self.page_size
        if cut < self._swap_min_rows():
            return None
        from ..ops.paged import HostKVEntry

        t0 = time.monotonic()
        if self.kv_layout == "paged":
            out = self._extract_pages(self._slot_pages[slot][: cut // self.page_size])
            out = {name: a[:, :cut] for name, a in out.items()}
        else:
            out = self._extract_rows(slot, cut)
        entry = HostKVEntry(
            rid=f"handoff-{req.rid}", tokens=tuple(row[:cut]),
            k=out["k"], v=out["v"],
            k_scale=out.get("ks"), v_scale=out.get("vs"),
        )
        self.flight.record(
            "handoff_export", rid=req.rid, slot=slot, tokens=cut,
            bytes=entry.nbytes, stall_s=round(time.monotonic() - t0, 6),
        )
        return entry

    def _swap_min_rows(self) -> int:
        """Rows below this aren't worth a host round trip. One page (the
        paged grain) — a swap replaces a model forward over the rows, so
        even small KV wins; recompute only beats the copy near zero rows."""
        return self.page_size if self.kv_layout == "paged" else 8

    def _swap_out(self, slot: int, sl: _Slot, reason: str) -> bool:  # acp: kv-seam
        """Offload a slot's written KV rows to the host pool right before
        its HBM pages are released (preemption, park expiry, mid-prefill
        deadline). The entry holds a bit-exact copy of rows [0, cut), so a
        later swap-in reproduces exactly what recompute would — greedy
        byte-identity is preserved by construction. Returns True when an
        entry landed; every failure path (pool off, rows too short, entry
        over budget, injected fault) degrades to today's discard-and-
        recompute behavior."""
        pool = self._host_pool
        if pool is None or self._stopping:
            return False
        req = sl.request
        if req.prewarm or req.truncated or sl.share_of is not None:
            # a waiting dedup follower's shared rows may not be written yet
            return False
        if sl.prefilling:
            rows = sl.prefill_pos
        elif sl.parked:
            rows = sl.park_cut
        else:
            rows = int(self._seq_lens[slot])
        row = self._full_row(req)
        cut = min(rows, len(row) - 1)  # strict prefix: resume must model >= 1 token
        if self.kv_layout == "paged":
            cut = (cut // self.page_size) * self.page_size
        if cut < self._swap_min_rows() and not (
            sl.prefilling and sl.swap_entry is not None
        ):
            # too few written rows to be worth a copy — except mid-restore,
            # where the consumed host entry can be re-put without any copy
            return False
        t0 = time.monotonic()
        if self._faults.enabled:
            if self._faults.pop("engine.host_swap_error") is not None:
                # the copy "failed": no entry lands, resume recomputes
                self.flight.record(
                    "swap_out", rid=req.rid, slot=slot, reason=reason,
                    error=True,
                )
                return False
            spec = self._faults.pop("engine.host_swap_slow")
            if spec is not None:
                # inside the timed window: the injected slowness IS the
                # host_stall the flight recorder should attribute
                time.sleep(float(spec.get("seconds", 0.05)))
        from ..ops.paged import HostKVEntry

        if sl.prefilling and sl.swap_entry is not None:
            # mid-restore victim: the WHOLE consumed entry is still in host
            # RAM — re-put it (zero copy, re-keyed to this request's rid so
            # the exact-match resume finds it) instead of re-extracting
            # only the rows that happened to land before the preemption.
            entry = sl.swap_entry
            if entry.rid != req.rid:
                entry = HostKVEntry(
                    rid=req.rid, tokens=entry.tokens, k=entry.k, v=entry.v,
                    k_scale=entry.k_scale, v_scale=entry.v_scale,
                )
            cut = entry.cut
        else:
            if self.kv_layout == "paged":
                rows = self._extract_pages(
                    self._slot_pages[slot][: cut // self.page_size]
                )
                rows = {name: a[:, :cut] for name, a in rows.items()}
            else:
                rows = self._extract_rows(slot, cut)
            entry = HostKVEntry(
                rid=req.rid, tokens=tuple(row[:cut]),
                k=rows["k"], v=rows["v"],
                k_scale=rows.get("ks"), v_scale=rows.get("vs"),
            )
        if not pool.put(entry):
            return False  # bigger than the whole budget: recompute instead
        stall = time.monotonic() - t0
        self.kv_swap_outs += 1
        REGISTRY.counter_add(
            "acp_engine_kv_swap_out_total", 1.0,
            help="KV offloads to the host-RAM tier (preemption, park "
            "expiry, and mid-prefill deadline drops that would otherwise "
            "discard written KV)",
        )
        if not req.prewarm:
            self.flight.record(
                "swap_out", rid=req.rid, slot=slot, reason=reason,
                tokens=cut, bytes=entry.nbytes, stall_s=round(stall, 6),
            )
        self._publish_memory_state()
        return True

    def _extract_pages(self, pages: list[int]) -> dict[str, np.ndarray]:  # acp: megastep-seam # acp: kv-seam
        """Gather paged KV pages to host numpy, token-major
        ``{"k"/"v": [L, nP, H, d]}`` plus ``"ks"/"vs": [L, nP, H]`` scale
        rows when the pool is quantized (the host tier carries the int8
        bytes verbatim — no requantization round trip). Dispatches
        decompose into pow2 page counts (bounded jit entries); the
        device->host copies are issued async and joined at the end so the
        DMA overlaps the remaining gathers."""
        P = self.page_size
        cfg = self.config
        chunks: list[dict] = []
        i = 0
        for n in _pow2_sizes(len(pages)):
            fn = self._jit_swap_gather.get(n)
            if fn is None:
                fn = jax.jit(
                    lambda c, ids: {name: a[:, ids] for name, a in c.items()}
                )
                self._jit_swap_gather[n] = fn
            ids = np.asarray(pages[i : i + n], dtype=np.int32)
            prof_t0 = self.profiler.start()
            out = fn(self.cache, self._put(ids))
            self.profiler.record(
                f"swap_gather[{n}]", prof_t0, out=out["k"], real_tokens=n * P
            )
            chunks.append(out)
            i += n
        for ch in chunks:
            for a in ch.values():
                if hasattr(a, "copy_to_host_async"):
                    a.copy_to_host_async()
        T = len(pages) * P
        out_np: dict[str, np.ndarray] = {}
        for name in self.cache:
            parts = [np.asarray(ch[name]) for ch in chunks]
            merged = np.concatenate(parts, axis=1)  # [L, nP_total, P, ...]
            out_np[name] = merged.reshape(
                (cfg.n_layers, T) + merged.shape[3:]
            )
        return out_np

    def _extract_rows(self, slot: int, cut: int) -> dict[str, np.ndarray]:  # acp: megastep-seam # acp: kv-seam
        """Slot layout: slice rows [0, cut) of ``slot`` out of the cache to
        host numpy ``{"k"/"v": [L, cut, H, d]}`` (+ scale rows for a
        quantized cache); pow2 sub-slices, async fetch."""
        L = self.config.n_layers
        chunks: list[dict] = []
        start = 0
        for n in _pow2_sizes(cut):
            fn = self._jit_swap_extract.get(n)
            if fn is None:

                def extract(cache, slot_, start_, n=n):
                    return {
                        name: jax.lax.dynamic_slice(
                            arr,
                            (0, slot_, start_) + (0,) * (arr.ndim - 3),
                            (L, 1, n) + arr.shape[3:],
                        )[:, 0]
                        for name, arr in cache.items()
                    }

                fn = jax.jit(extract)  # read-only: cache NOT donated
                self._jit_swap_extract[n] = fn
            prof_t0 = self.profiler.start()
            out = fn(self.cache, jnp.int32(slot), jnp.int32(start))
            self.profiler.record(
                f"swap_extract[{n}]", prof_t0, out=out["k"], real_tokens=n
            )
            chunks.append(out)
            start += n
        for ch in chunks:
            for a in ch.values():
                if hasattr(a, "copy_to_host_async"):
                    a.copy_to_host_async()
        return {
            name: np.concatenate([np.asarray(ch[name]) for ch in chunks], axis=1)
            for name in self.cache
        }

    def _swap_in_rows(self, slot: int, entry, start: int, n: int) -> float:  # acp: megastep-seam # acp: kv-seam
        """Restore rows [start, start+n) of a host entry into ``slot``'s
        KV (page-aligned in paged mode — callers schedule page-grain
        chunks). Returns the engine-thread seconds spent blocked in the
        host->device copies (the host_stall phase input)."""
        t0 = time.monotonic()
        rows = {"k": entry.k, "v": entry.v}
        if "ks" in self.cache:
            # quantized cache: the entry MUST carry matching scale rows (a
            # bf16 entry cannot restore into an int8 pool) — _swap_out on a
            # quantized engine always records them
            rows["ks"] = entry.k_scale
            rows["vs"] = entry.v_scale
        if self.kv_layout == "paged":
            P = self.page_size
            pages = self._slot_pages[slot][start // P : (start + n) // P]
            i = 0
            for m in _pow2_sizes(len(pages)):
                fn = self._jit_swap_scatter.get(m)
                if fn is None:
                    fn = jax.jit(
                        lambda c, ids, blocks: {
                            name: c[name].at[:, ids].set(blocks[name])
                            for name in c
                        },
                        donate_argnums=(0,),
                    )
                    self._jit_swap_scatter[m] = fn
                ids = np.asarray(pages[i : i + m], dtype=np.int32)
                lo = start + i * P
                blocks = {
                    name: a[:, lo : lo + m * P].reshape(
                        a.shape[0], m, P, *a.shape[2:]
                    )
                    for name, a in rows.items()
                }
                prof_t0 = self.profiler.start()
                self.cache = fn(
                    self.cache, self._put(ids),
                    {name: self._put(b) for name, b in blocks.items()},
                )
                self.profiler.record(
                    f"swap_scatter[{m}]", prof_t0, out=self.cache["k"],
                    real_tokens=m * P,
                )
                i += m
        else:
            pos = start
            while pos < start + n:
                m = _pow2_sizes(start + n - pos)[0]
                fn = self._jit_swap_restore.get(m)
                if fn is None:

                    def restore(cache, slot_, start_, blocks):
                        return {
                            name: jax.lax.dynamic_update_slice(
                                arr, blocks[name][:, None],
                                (0, slot_, start_) + (0,) * (arr.ndim - 3),
                            )
                            for name, arr in cache.items()
                        }

                    fn = jax.jit(restore, donate_argnums=(0,))
                    self._jit_swap_restore[m] = fn
                prof_t0 = self.profiler.start()
                self.cache = fn(
                    self.cache, jnp.int32(slot), jnp.int32(pos),
                    {
                        name: self._put(a[:, pos : pos + m])
                        for name, a in rows.items()
                    },
                )
                self.profiler.record(
                    f"swap_restore[{m}]", prof_t0, out=self.cache["k"],
                    real_tokens=m,
                )
                pos += m
        return time.monotonic() - t0

    def _swap_in_cut(self, sl: _Slot) -> int:
        """Rows a mid-restore slot will take from its host entry — the
        entry's cut, never past the strict-prefix edge of this row."""
        cut = min(sl.swap_entry.cut, len(sl.prefill_row) - 1)
        if self.kv_layout == "paged":
            cut = (cut // self.page_size) * self.page_size
        return cut

    def _finish_swap_in(self, slot: int, sl: _Slot) -> None:
        """The restore reached its cut: the slot becomes a plain mid-
        prefill slot (model chunks take over for the remaining suffix)."""
        req = sl.request
        self.kv_swap_ins += 1
        REGISTRY.counter_add(
            "acp_engine_kv_swap_in_total", 1.0,
            help="host-tier KV restores completed (re-admissions that "
            "swapped rows back in instead of re-running prefill)",
        )
        if not req.prewarm:
            self.flight.record(
                "swap_in", rid=req.rid, slot=slot, tokens=sl.prefill_pos,
                stall_s=round(sl.swap_stall_s, 6),
            )
        sl.swap_entry = None
        self._publish_memory_state()

    def _unshare_followers(self, leader_slot: int, leader_sl: _Slot) -> None:
        """A mid-prefill dedup leader is leaving (preempt/expire/cancel):
        rewind every waiting follower to the page-aligned rows the leader
        actually wrote. The shared pages survive (followers hold refs), so
        rows below the rewind stay valid; each follower then recomputes
        the gap itself — multiple followers write bit-identical KV into
        the shared pages, so redundant writes are harmless."""
        if not leader_sl.prefilling:
            return  # leader finished its prefill: every shared row is written
        pos = (leader_sl.prefill_pos // self.page_size) * self.page_size
        rid = leader_sl.request.rid
        for s, sl in self._slots.items():
            if (
                sl.prefilling
                and sl.share_of is not None
                and sl.share_of[0] == leader_slot
                and sl.share_of[1] == rid
            ):
                if sl.prefill_pos > pos:
                    # the follower re-runs rows its dead leader had covered:
                    # the leader's compute of them is now waste
                    self.profiler.reclassify(
                        "dedup_rewind", sl.prefill_pos - pos
                    )
                    sl.prefill_pos = pos
                    self._seq_lens[s] = pos
                sl.share_of = None

    def _match_dedup_leader(
        self, full: list[int], group: Optional[list] = None
    ) -> Optional[tuple]:
        """Longest page-aligned common prefix between ``full`` and a live
        slot's row — or an earlier member of the admission group being
        formed (the burst case: N same-persona tasks arriving at once,
        before any prefill could seed the cache). Returns
        ``(leader_slot, leader_rid, cut)`` or None. Parked leaders share up
        to their park cut (rows resident); active/prefilling leaders up to
        their whole row — a follower behind a still-prefilling leader
        waits for the shared rows to be written (see _prefill_chunks).
        Slots that are themselves waiting dedup followers are skipped
        (their prefill_pos counts rows their OWN leader hasn't written, so
        the follower-wait test would lie); ties keep the first candidate,
        so a burst chains every follower to the one root writer."""
        if self.kv_layout != "paged" or not self.prefix_dedup:
            return None
        best: Optional[tuple] = None
        for s, sl in self._slots.items():
            if sl.share_of is not None:
                continue
            # avoid rebuilding rows per scan in the admission path: parked
            # slots compare against the prompt capped at the park cut, and
            # every other slot carries its row as prefill_row (kept after
            # the prefill flip precisely so hot paths don't reconstruct it)
            if sl.parked:
                other, limit = sl.request.prompt, sl.park_cut
            else:
                other = (
                    sl.prefill_row
                    if sl.prefill_row is not None
                    else self._full_row(sl.request)
                )
                limit = len(other)
            cut = self._common_cut(full, other, limit)
            if cut >= self._swap_min_rows() and (best is None or cut > best[2]):
                best = (s, sl.request.rid, cut)
        for g_req, g_slot, _g_pages, g_match in group or ():
            if g_match is not None and (
                g_match[1].get("share_of") is not None
                or g_match[1].get("swap") is not None
            ):
                continue  # follower/mid-restore: not a safe root writer
            cut = self._common_cut(full, self._full_row(g_req))
            if cut >= self._swap_min_rows() and (best is None or cut > best[2]):
                best = (g_slot, g_req.rid, cut)
        return best

    def _common_cut(
        self, full: list[int], other: list[int], limit: Optional[int] = None
    ) -> int:
        """Page-aligned length of the longest shared token prefix, capped
        strictly below ``full``'s end (suffix tokens must remain) and at
        ``limit`` (e.g. a parked leader's resident rows). Compared a page
        at a time (C-speed list-slice equality) — the result is rounded
        down to a page boundary anyway, and a per-token Python loop over
        multi-k prefixes would tax the engine thread exactly during the
        admission bursts dedup exists to speed up."""
        P = self.page_size
        n = min(len(full) - 1, len(other))
        if limit is not None:
            n = min(n, limit)
        pages = n // P
        i = 0
        while i < pages and full[i * P : (i + 1) * P] == other[i * P : (i + 1) * P]:
            i += 1
        return i * P

    def _publish_memory_state(self) -> None:
        """Refresh the cross-thread memory mirrors + gauges from engine-
        thread truth (host pool bytes/entries, refcount-shared pages).
        Cheap; runs after every dispatch cycle and at each swap/share."""
        if self._host_pool is not None:
            self._host_kv_used = self._host_pool.used_bytes
            self._host_kv_entries = len(self._host_pool)
            REGISTRY.gauge_set(
                "acp_engine_host_kv_bytes", float(self._host_kv_used),
                help="bytes of swapped-out KV resident in the host-RAM "
                "offload tier (bounded by --tpu-host-kv-bytes)",
            )
        else:
            self._host_kv_used = 0
            self._host_kv_entries = 0
        # allocated_count is a len() read, same atomic contract as
        # free_count; every allocated page of a quantized pool holds int8
        # KV + its scale rows. Published unconditionally so knobs-off and
        # slot-layout engines export an explicit 0 (dashboards comparing
        # enabled-vs-disabled deploys need a present series, not a gap).
        REGISTRY.gauge_set(
            "acp_engine_quantized_kv_pages",
            float(
                self._allocator.allocated_count
                if self.kv_layout == "paged" and self.quantize_kv
                else 0
            ),
            help="allocated KV pages currently holding int8-"
            "quantized KV (with per-row scale storage); 0 unless "
            "quantize_kv is on",
        )
        if self.kv_layout == "paged":
            self._prefix_shared_pages = self._allocator.shared_count
            REGISTRY.gauge_set(
                "acp_engine_prefix_shared_pages",
                float(self._prefix_shared_pages),
                help="HBM KV pages currently refcount-shared by more than "
                "one owner (cross-request shared-prefix dedup + prefix "
                "cache)",
            )
