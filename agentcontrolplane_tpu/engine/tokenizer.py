"""Tokenizers + the Llama-3 chat template.

Two tokenizer implementations behind one tiny interface:

- ``HFTokenizer`` — wraps a ``tokenizer.json`` via the ``tokenizers`` library
  (real checkpoints).
- ``ByteTokenizer`` — bytes + special tokens; zero-asset fallback used by
  tests and randomly-initialised benchmark serving.

The chat template mirrors Llama-3's header format; tool-call turns follow the
JSON convention parsed by ``toolparse`` (tool schemas are injected into the
system prompt, assistant tool calls are serialized JSON, tool results arrive
as ``ipython`` turns).
"""

from __future__ import annotations

import json
from typing import Optional, Protocol, Sequence

from ..api.resources import Message
from ..llmclient.base import Tool

BOT = "<|begin_of_text|>"
EOT = "<|eot_id|>"
EOS = "<|end_of_text|>"
SH = "<|start_header_id|>"
EH = "<|end_header_id|>"

SPECIALS = [BOT, EOS, SH, EH, EOT, "<|python_tag|>", "<|pad|>", "<|unk|>"]

ROLE_HEADER = {"system": "system", "user": "user", "assistant": "assistant", "tool": "ipython"}


class Tokenizer(Protocol):
    def encode(self, text: str) -> list[int]: ...
    def decode(self, tokens: Sequence[int]) -> str: ...
    @property
    def stop_tokens(self) -> set[int]: ...
    @property
    def vocab_size(self) -> int: ...


class ByteTokenizer:
    """UTF-8 bytes at ids 0-255; specials from 256."""

    def __init__(self):
        self._specials = {s: 256 + i for i, s in enumerate(SPECIALS)}
        self._specials_rev = {v: k for k, v in self._specials.items()}

    @property
    def vocab_size(self) -> int:
        return 256 + len(SPECIALS)

    @property
    def stop_tokens(self) -> set[int]:
        return {self._specials[EOT], self._specials[EOS]}

    def encode(self, text: str) -> list[int]:
        out: list[int] = []
        i = 0
        while i < len(text):
            if text[i] == "<":
                matched = False
                for s, tid in self._specials.items():
                    if text.startswith(s, i):
                        out.append(tid)
                        i += len(s)
                        matched = True
                        break
                if matched:
                    continue
            out.extend(text[i].encode("utf-8"))
            i += 1
        return out

    def decode(self, tokens: Sequence[int]) -> str:
        parts: list[str] = []
        buf = bytearray()
        for t in tokens:
            if t >= 256:
                if buf:
                    parts.append(buf.decode("utf-8", errors="replace"))
                    buf = bytearray()
                parts.append(self._specials_rev.get(t, ""))
            else:
                buf.append(t)
        if buf:
            parts.append(buf.decode("utf-8", errors="replace"))
        return "".join(parts)

    def token_bytes(self, token: int) -> bytes | None:
        """Byte expansion for grammar-constrained decoding (None = special)."""
        if 0 <= token < 256:
            return bytes([token])
        return None


class HFTokenizer:
    """tokenizer.json wrapper (Llama-3 checkpoints)."""

    def __init__(self, path: str):
        from tokenizers import Tokenizer as _Tok

        self._tok = _Tok.from_file(path)

    @property
    def vocab_size(self) -> int:
        return self._tok.get_vocab_size()

    @property
    def stop_tokens(self) -> set[int]:
        out = set()
        for s in (EOT, EOS):
            tid = self._tok.token_to_id(s)
            if tid is not None:
                out.add(tid)
        return out

    def encode(self, text: str) -> list[int]:
        return self._tok.encode(text, add_special_tokens=False).ids

    def decode(self, tokens: Sequence[int]) -> str:
        return self._tok.decode(list(tokens), skip_special_tokens=False)

    def token_bytes(self, token: int) -> bytes | None:
        """Byte expansion via the byte-level-BPE unicode alphabet (the GPT-2
        char<->byte table Llama-3 tokenizers use). None for specials."""
        s = self._tok.id_to_token(token)
        if s is None or (s.startswith("<|") and s.endswith("|>")):
            return None
        table = _bytelevel_char_to_byte()
        out = bytearray()
        for ch in s:
            b = table.get(ch)
            if b is None:
                return None  # not a byte-level token (added/special)
            out.append(b)
        return bytes(out)


def _bytelevel_char_to_byte() -> dict[str, int]:
    """Inverse of GPT-2's bytes_to_unicode mapping (standard byte-level BPE
    alphabet)."""
    global _BYTELEVEL_TABLE
    if _BYTELEVEL_TABLE is None:
        bs = list(range(ord("!"), ord("~") + 1)) + list(
            range(ord("\xa1"), ord("\xac") + 1)
        ) + list(range(ord("\xae"), ord("\xff") + 1))
        cs = bs[:]
        n = 0
        for b in range(256):
            if b not in bs:
                bs.append(b)
                cs.append(256 + n)
                n += 1
        _BYTELEVEL_TABLE = {chr(c): b for b, c in zip(bs, cs)}
    return _BYTELEVEL_TABLE


_BYTELEVEL_TABLE: dict[str, int] | None = None


# ---------------------------------------------------------------------------
# Chat template
# ---------------------------------------------------------------------------

TOOL_INSTRUCTIONS = """

You have access to the following tools. To call a tool, respond with ONLY a
JSON object of the form {{"name": "<tool-name>", "arguments": {{...}}}} and
nothing else. To answer the user directly, respond with plain text.

Available tools:
{tools}"""


def render_system(system: str, tools: Sequence[Tool]) -> str:
    if not tools:
        return system
    tool_lines = "\n".join(
        json.dumps(
            {
                "name": t.function.name,
                "description": t.function.description,
                "parameters": t.function.parameters,
            }
        )
        for t in tools
    )
    return system + TOOL_INSTRUCTIONS.format(tools=tool_lines)


def _turn(role: str, content: str) -> str:
    # content is trimmed exactly like the official Llama-3 chat template's
    # ``message['content'] | trim`` — verified token-for-token against HF
    # transformers' apply_chat_template in tests/engine/test_golden_fidelity.py
    return f"{SH}{ROLE_HEADER[role]}{EH}\n\n{content.strip()}{EOT}"


def render_turns(
    messages: Sequence[Message], tools: Sequence[Tool]
) -> list[tuple[str, str]]:
    """Context window -> [(role, rendered_segment), ...] — the building
    blocks :func:`render_prompt` concatenates. Exposed separately so
    training can mask loss to assistant segments (every segment starts at
    a special-token boundary, so per-segment tokenization concatenates to
    the whole-prompt tokenization)."""
    parts: list[tuple[str, str]] = [("bot", BOT)]
    rendered_system = False
    for m in messages:
        if m.role == "system" and not rendered_system:
            parts.append(("system", _turn("system", render_system(m.content, tools))))
            rendered_system = True
            continue
        if m.role == "assistant" and m.tool_calls:
            calls = [
                {
                    "name": tc.function.name,
                    "arguments": json.loads(tc.function.arguments or "{}"),
                }
                for tc in m.tool_calls
            ]
            body = "\n".join(json.dumps(c) for c in calls)
            parts.append(("assistant", _turn("assistant", body)))
            continue
        parts.append((m.role, _turn(m.role, m.content)))
    if not rendered_system and tools:
        parts.insert(1, ("system", _turn("system", render_system("", tools))))
    return parts


def render_prompt(messages: Sequence[Message], tools: Sequence[Tool]) -> str:
    """Context window -> Llama-3 chat prompt ending at an open assistant turn."""
    parts = [t for _, t in render_turns(messages, tools)]
    parts.append(f"{SH}assistant{EH}\n\n")
    return "".join(parts)
