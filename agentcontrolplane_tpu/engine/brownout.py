"""Degradation ladder: shed optional features under sustained pressure.

When the engine is genuinely overloaded — admission sheds landing, the
dispatch watchdog recording stalls — the right move is not to degrade
correctness but to *turn off the optional work* in a pinned order, one
bounded step per interval, and to restore everything the moment pressure
lifts. This mirrors the autopilot's apply-seam exactly: a pure-ish
controller decides, the ENGINE applies the knob change and flight-records
it, and every level move publishes ``acp_engine_brownout_level``.

The ladder (each rung sheds strictly-optional capacity, never output
bytes — every knob it touches carries a byte-identity contract):

1. ``spec_len`` → 0      — speculative decoding off: verify dispatches are
   extra compute the moment acceptance pays for itself and pure waste the
   moment the engine is starved.
2. ``park_max_s`` → 0    — park acceptance off: parked slots are
   speculative capacity held against a FUTURE turn; under pressure the
   present turn needs the pages more. (Submissions already parked keep
   their contract; only NEW parks stop.)
3. ``planner_max_quota`` → 1 — chunk quota floor: deadline-driven
   multi-chunk bursts yield to fair one-chunk-per-cycle progress.

Pressure is counter deltas, not wall clock: ``step`` consumes the
cumulative shed and stall counters and judges the delta since the last
tick. Like the autopilot, the controller is interval-gated on busy engine
cycles and moves at most ONE rung per tick in either direction, with
separate down/up streak requirements so a single calm interval doesn't
whipsaw a loaded engine back into speculative work.

Off by default (``Engine(brownout=False)``); constructor-disabled under
multi-host coordination (shed/stall counts are host-local — divergent
knobs would fork lockstep admission shapes, the same rule as the
autopilot).
"""

from __future__ import annotations

from dataclasses import dataclass

# the pinned ladder order: (knob, browned-out value)
LADDER: tuple[tuple[str, object], ...] = (
    ("spec_len", 0),
    ("park_max_s", 0.0),
    ("planner_max_quota", 1),
)


@dataclass(frozen=True)
class BrownoutPolicy:
    """Pressure thresholds and hysteresis for the ladder controller."""

    interval: int = 64          # busy cycles between controller decisions
    shed_threshold: int = 1     # sheds-per-interval that count as pressure
    stall_threshold: int = 1    # stalls-per-interval that count as pressure
    down_after: int = 1         # consecutive pressured ticks -> step down
    up_after: int = 2           # consecutive calm ticks -> step up


class BrownoutController:
    """Thin stateful judge around the pressure deltas: counts engine
    cycles, and every ``interval`` busy cycles emits the target level
    (0 = full service, ``len(LADDER)`` = fully browned out). The ENGINE
    applies the rung (saving/restoring knob values) and flight-records
    it — the controller never touches engine state, so the policy is
    unit-testable without an engine."""

    def __init__(self, policy: BrownoutPolicy | None = None):
        self.policy = policy or BrownoutPolicy()
        self.level = 0
        self.cycles = 0
        self.steps_down = 0
        self.steps_up = 0
        self._last_sheds = 0
        self._last_stalls = 0
        self._pressure_streak = 0
        self._calm_streak = 0

    def due(self) -> bool:
        """Count one busy engine cycle; True on interval boundaries
        (split from :meth:`step` like Autopilot.due, so the engine only
        gathers inputs on ticks that will use them)."""
        self.cycles += 1
        return self.cycles % self.policy.interval == 0

    def step(self, sheds: int, stalls: int) -> int:
        """One controller decision from the CUMULATIVE shed/stall
        counters; returns the new target level (moves at most one rung)."""
        p = self.policy
        d_sheds = max(0, sheds - self._last_sheds)
        d_stalls = max(0, stalls - self._last_stalls)
        self._last_sheds = sheds
        self._last_stalls = stalls
        pressured = d_sheds >= p.shed_threshold or d_stalls >= p.stall_threshold
        if pressured:
            self._pressure_streak += 1
            self._calm_streak = 0
            if self._pressure_streak >= p.down_after and self.level < len(LADDER):
                self.level += 1
                self.steps_down += 1
                self._pressure_streak = 0
        else:
            self._calm_streak += 1
            self._pressure_streak = 0
            if self._calm_streak >= p.up_after and self.level > 0:
                self.level -= 1
                self.steps_up += 1
                self._calm_streak = 0
        return self.level


__all__ = ["LADDER", "BrownoutController", "BrownoutPolicy"]
