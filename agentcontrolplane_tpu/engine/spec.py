"""Model-free speculative decoding: the n-gram prompt-lookup drafter and
the per-slot adaptive draft-length controller.

Agent traffic is the most self-repetitive LLM workload there is: tool-call
JSON echoes schema keys from the prompt, ReAct loops restate tool outputs,
and code edits copy spans verbatim. The drafter exploits exactly that
structure without any draft model: match the tail of ``prompt + generated``
against an earlier occurrence of the same n-gram and propose the tokens
that followed it. Drafts are free to be WRONG — the engine's batched verify
pass (models/llama.py ``verify_continue``/``verify_paged_continue`` +
ops/sampling.py ``speculative_accept``) scores every proposed position in
one dispatch and only the model-agreeing prefix advances the sequence, so
greedy outputs stay byte-identical to the non-speculative engine.

Everything here is HOST-ONLY state: a preempted slot carries nothing extra
to save (the controller is simply rebuilt at re-admission), and a crash
rebuild starts fresh.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

# verify dispatches a slot spends at draft length 0 before probing again
# with a 1-token draft — without this a slot that decayed to 0 (its text
# stopped being self-similar) could never rejoin speculation even after the
# generation becomes repetitive again
REPROBE_DISPATCHES = 16

# candidate match positions examined per n-gram length: drafting runs for
# every slot on every verify dispatch, and a common trailing byte (space,
# quote) can occur thousands of times in a long context — an unbounded
# Python-level match walk is O(occurrences) host work in the decode hot
# loop. The most recent matches are the likeliest continuations anyway
# (agent loops restate their LATEST tool output), so capping the walk
# loses only distant repeats. The remaining per-n cost is the vectorized
# first-token scan, O(ctx) in C.
MAX_HEADS_PER_N = 64


def ngram_propose(ctx: np.ndarray, ngram_max: int, max_len: int) -> list[int]:
    """Prompt-lookup draft: match the trailing n-gram of ``ctx`` (n from
    ``ngram_max`` down to 1, longest first) against an earlier occurrence
    and propose up to ``max_len`` of the tokens that followed it.

    Candidate priority: a match whose continuation fills ``max_len`` wins
    immediately, scanning MOST RECENT first (agent loops restate their
    latest tool output, not their oldest); otherwise the longest available
    continuation wins, with larger n and recency as tie-breaks. The
    length-first rule matters for repetition attractors: in a tight loop
    the most recent match always sits near the context edge with only a
    token or two of continuation, while one period earlier the identical
    match yields a full-length draft. Returns [] when nothing matches — a
    free outcome (the slot rides the dispatch with an empty draft, or the
    whole engine falls back to the plain decode block)."""
    n_ctx = int(ctx.shape[0])
    if n_ctx < 2 or max_len <= 0:
        return []
    best: list[int] = []
    for n in range(min(ngram_max, n_ctx - 1), 0, -1):
        pat = ctx[n_ctx - n :]
        # candidate window starts strictly before the tail's own window;
        # overlap WITH the tail window is allowed (period < n repetition)
        heads = np.flatnonzero(ctx[: n_ctx - n] == pat[0])
        for i in heads[-MAX_HEADS_PER_N:][::-1]:  # most recent first
            if not np.array_equal(ctx[i : i + n], pat):
                continue
            draft = ctx[i + n : i + n + max_len]
            if draft.size >= max_len:
                return [int(t) for t in draft]
            if draft.size > len(best):  # strict: larger n / recency keep ties
                best = [int(t) for t in draft]
    return best


@dataclass
class SpecState:
    """Per-slot adaptive draft length (AIMD-flavored): full rejection halves
    the cap (an adversarial slot decays 8 -> 4 -> 2 -> 1 -> 0, i.e. all the
    way back to today's non-speculative path — never below it), partial
    acceptance nudges it up additively, full acceptance doubles it back
    toward the engine cap. A slot parked at 0 re-probes with a 1-token
    draft every :data:`REPROBE_DISPATCHES` dispatches."""

    limit: int  # the engine's --tpu-spec-len cap
    cur: int = -1  # current cap; -1 = start optimistic at limit
    idle: int = 0  # dispatches spent at cur == 0 (re-probe timer)

    def __post_init__(self) -> None:
        if self.cur < 0:
            self.cur = self.limit

    def cap(self) -> int:
        """Draft-length cap for the next dispatch (ticks the re-probe
        timer while parked at 0)."""
        if self.cur == 0:
            self.idle += 1
            if self.idle >= REPROBE_DISPATCHES:
                self.cur, self.idle = 1, 0
        return self.cur

    def observe(self, proposed: int, accepted: int) -> None:
        """Feed back one verify dispatch's outcome for this slot."""
        if proposed <= 0:
            return  # no draft rode this dispatch: nothing was learned
        if accepted == 0:
            self.cur //= 2
        elif accepted >= proposed:
            self.cur = min(self.limit, max(1, self.cur * 2))
        else:
            self.cur = min(self.limit, self.cur + 1)
