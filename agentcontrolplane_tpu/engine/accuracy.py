"""Byte-identity-relaxed accuracy gate for quantized serving.

Greedy byte-identity is this repo's load-bearing correctness contract:
every serving mechanism (chunking, spec decode, megastep fusion, KV
tiers) is pinned bit-for-bit against the plain path. Quantization is the
one knob that LEGITIMATELY breaks it — int8 weights and int8 KV are a
different (deliberately close) function. This module is the replacement
contract: a pinned deterministic fixture is scored through the REAL
serving numerics (prefill writes + per-step decode reads against the
slot cache, exactly the hot loop's read/write discipline) under the
quantized configuration and under the bf16 baseline, and the gate
asserts

- **top-1 greedy agreement** — the fraction of positions whose argmax
  token matches the bf16 path — stays >= a pinned threshold, and
- **logit MAE** — mean |quantized - bf16| over the fixture's logits —
  stays <= a pinned bound.

Tests pin the thresholds (tests/engine/test_quant_kv.py); the bench
fixture (``ACP_BENCH_QUANT=1``) records the measured numbers into the
PR's bench doc so the accuracy trajectory is inspectable next to the
capacity multiplier it buys. Both knobs off remains covered by the
existing byte-identity matrix — this gate never relaxes that.
"""

from __future__ import annotations

from functools import lru_cache, partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..models.llama import (
    LlamaConfig,
    decode_step,
    init_kv_cache,
    prefill_batch,
)
from ..ops.quant import quantize_params


def pinned_fixture(
    vocab_size: int, prompts: int = 4, length: int = 48, seed: int = 20260804
) -> np.ndarray:
    """The gate's deterministic prompt set: ``[prompts, length]`` int32
    rows drawn from a fixed seed (token 0 reserved out, matching the
    tokenizers' pad/special conventions). Same (vocab, shape, seed) ->
    same fixture forever — changing any of these is changing the
    contract, not re-rolling it."""
    rng = np.random.default_rng(seed)
    return rng.integers(1, vocab_size, size=(prompts, length)).astype(np.int32)


@lru_cache(maxsize=8)
def _jitted(config: LlamaConfig):
    # one jitted pair per config: a fresh jax.jit wrapper per call would
    # recompile every shape on every report (LlamaConfig is frozen/hashable)
    return (
        jax.jit(partial(prefill_batch, config=config)),
        jax.jit(partial(decode_step, config=config)),
    )


def teacher_forced_logits(
    params: dict,
    config: LlamaConfig,
    rows: np.ndarray,  # [B, T] int32 — equal-length fixture rows
    quantize_kv: bool = False,
) -> np.ndarray:
    """Serving-numerics logits at every position: the first token prefills
    a (optionally int8) slot cache, then each following token is teacher-
    forced through ``decode_step`` — so position ``t``'s logits are
    computed reading the cache exactly as the engine's decode loop reads
    it (quantized rows dequantize after the gather; fresh K/V quantizes on
    commit). Returns [B, T, V] float32; ``logits[:, t]`` scores the token
    following ``rows[:, t]``."""
    B, T = rows.shape
    cache = init_kv_cache(config, B, T, quantize_kv=quantize_kv)
    slots = jnp.arange(B, dtype=jnp.int32)
    ones = jnp.ones(B, dtype=jnp.int32)
    active = jnp.ones(B, dtype=bool)
    jit_prefill, jit_decode = _jitted(config)
    cache, logits = jit_prefill(
        params, cache, jnp.asarray(rows[:, :1]), ones, slots
    )
    out = [np.asarray(logits)]
    for t in range(1, T):
        cache, logits = jit_decode(
            params, cache,
            jnp.asarray(rows[:, t]),
            jnp.full((B,), t, dtype=jnp.int32),
            active=active,
        )
        out.append(np.asarray(logits))
    return np.stack(out, axis=1).astype(np.float32)


def accuracy_report(
    config: LlamaConfig,
    params: dict,
    *,
    quantize_weights: bool = False,
    quantize_kv: bool = False,
    rows: Optional[np.ndarray] = None,
    baseline: Optional[np.ndarray] = None,
) -> dict:
    """Score one quantized configuration against the bf16 baseline over
    the pinned fixture. ``params`` are the DENSE params (the weight-
    quantized run derives its int8 copy via ``quantize_params``, so both
    runs serve the same underlying function). ``baseline`` optionally
    supplies the bf16 :func:`teacher_forced_logits` for these ``rows``
    (callers scoring several configurations pay the baseline pass once).
    Returns the gate metrics::

        {"top1_agreement": float, "logit_mae": float,
         "positions": int, "quantize_weights": bool, "quantize_kv": bool}
    """
    if rows is None:
        rows = pinned_fixture(config.vocab_size)
    base = baseline if baseline is not None else teacher_forced_logits(
        params, config, rows, quantize_kv=False
    )
    qparams = quantize_params(params) if quantize_weights else params
    cand = teacher_forced_logits(qparams, config, rows, quantize_kv=quantize_kv)
    agree = float(np.mean(base.argmax(-1) == cand.argmax(-1)))
    mae = float(np.mean(np.abs(base - cand)))
    return {
        "top1_agreement": round(agree, 4),
        "logit_mae": round(mae, 5),
        "positions": int(base.shape[0] * base.shape[1]),
        "quantize_weights": bool(quantize_weights),
        "quantize_kv": bool(quantize_kv),
    }


def check_accuracy_gate(
    report: dict, min_top1: float, max_logit_mae: float
) -> list[str]:
    """Evaluate a report against pinned thresholds; returns violations
    (empty = the gate passes). Split from :func:`accuracy_report` so the
    bench fixture can record the numbers AND the gate verdict."""
    problems: list[str] = []
    if report["top1_agreement"] < min_top1:
        problems.append(
            f"top-1 greedy agreement {report['top1_agreement']} < pinned "
            f"threshold {min_top1}"
        )
    if report["logit_mae"] > max_logit_mae:
        problems.append(
            f"logit MAE {report['logit_mae']} > pinned bound {max_logit_mae}"
        )
    return problems
