"""Tracing: spans whose root context is checkpointed into object status.

The reference's clever bit (SURVEY.md §5): the Task's root span context is
persisted in CR status at initialization (``task/state_machine.go:122-137``)
and reconstructed on every reconcile (``task_helpers.go:58-81``), so one
logical trace spans many reconciles (and, in multi-replica deployments,
many processes). We reproduce that: ``Tracer`` mints W3C-style hex ids,
keeps finished spans in a ring buffer for inspection/REST exposure, and
optionally exports OTLP-JSON over HTTP if ``OTEL_EXPORTER_OTLP_ENDPOINT`` is
set (silent no-op fallback, ``internal/otel/otel.go:23-54``).
"""

from __future__ import annotations

import collections
import json
import os
import queue
import secrets
import threading
import time
import urllib.request
from dataclasses import dataclass, field
from typing import Any, Optional

from ..api.resources import SpanContext


def new_trace_id() -> str:
    return secrets.token_hex(16)


def new_span_id() -> str:
    return secrets.token_hex(8)


@dataclass
class Span:
    name: str
    trace_id: str
    span_id: str
    parent_span_id: str = ""
    start_time: float = field(default_factory=time.time)
    end_time: Optional[float] = None
    attributes: dict[str, Any] = field(default_factory=dict)
    status: str = "OK"

    def set_attribute(self, key: str, value: Any) -> None:
        self.attributes[key] = value

    def context(self) -> SpanContext:
        return SpanContext(trace_id=self.trace_id, span_id=self.span_id)

    @property
    def duration(self) -> float:
        return (self.end_time or time.time()) - self.start_time


class Tracer:
    def __init__(self, max_finished: int = 4096, endpoint: Optional[str] = None):
        # endpoint: None = use env (no-op if unset); "" = explicitly disabled
        if endpoint is None:
            endpoint = os.environ.get("OTEL_EXPORTER_OTLP_ENDPOINT", "")
        self.endpoint = endpoint
        self.finished: collections.deque[Span] = collections.deque(maxlen=max_finished)
        self._lock = threading.Lock()
        # exports run on a dedicated daemon thread so span ends never block
        # the asyncio reconcile loop
        self._export_queue: "queue.Queue[Optional[Span]]" = queue.Queue(maxsize=1024)
        self._export_thread: Optional[threading.Thread] = None

    def _ensure_export_thread(self) -> None:
        if self._export_thread is None or not self._export_thread.is_alive():
            self._export_thread = threading.Thread(
                target=self._export_loop, name="otlp-export", daemon=True
            )
            self._export_thread.start()

    def _export_loop(self) -> None:
        while True:
            span = self._export_queue.get()
            if span is None:
                return
            self._export(span)

    def start_span(
        self,
        name: str,
        parent: Optional[SpanContext] = None,
        attributes: dict[str, Any] | None = None,
    ) -> Span:
        if parent is not None and parent.trace_id:
            trace_id, parent_span_id = parent.trace_id, parent.span_id
        else:
            trace_id, parent_span_id = new_trace_id(), ""
        return Span(
            name=name,
            trace_id=trace_id,
            span_id=new_span_id(),
            parent_span_id=parent_span_id,
            attributes=dict(attributes or {}),
        )

    def end_span(
        self, span: Span, status: str = "OK", end_time: Optional[float] = None
    ) -> None:
        """Finish ``span`` (now, or at an explicit historical ``end_time``
        — the flight recorder reconstructs engine phase spans from its
        monotonic event stream after the fact, so both endpoints of those
        spans are in the past)."""
        span.end_time = time.time() if end_time is None else end_time
        span.status = status
        with self._lock:
            self.finished.append(span)
        if self.endpoint:
            self._ensure_export_thread()
            try:
                self._export_queue.put_nowait(span)
            except queue.Full:
                pass  # drop rather than block

    def _export(self, span: Span) -> None:
        """Best-effort OTLP/JSON export; failures are silent (no-op fallback)."""
        body = {
            "resourceSpans": [
                {
                    "resource": {
                        "attributes": [
                            {"key": "service.name", "value": {"stringValue": "acp-tpu"}}
                        ]
                    },
                    "scopeSpans": [
                        {
                            "spans": [
                                {
                                    "traceId": span.trace_id,
                                    "spanId": span.span_id,
                                    "parentSpanId": span.parent_span_id,
                                    "name": span.name,
                                    "startTimeUnixNano": int(span.start_time * 1e9),
                                    "endTimeUnixNano": int((span.end_time or time.time()) * 1e9),
                                    "attributes": [
                                        {"key": k, "value": {"stringValue": str(v)}}
                                        for k, v in span.attributes.items()
                                    ],
                                }
                            ]
                        }
                    ],
                }
            ]
        }
        try:
            req = urllib.request.Request(
                self.endpoint.rstrip("/") + "/v1/traces",
                data=json.dumps(body).encode(),
                headers={"Content-Type": "application/json"},
            )
            urllib.request.urlopen(req, timeout=2.0)
        except Exception:
            pass

    def spans_for_trace(self, trace_id: str) -> list[Span]:
        with self._lock:
            return [s for s in self.finished if s.trace_id == trace_id]


NOOP_TRACER = Tracer(endpoint="")  # explicitly disabled, ignores env
