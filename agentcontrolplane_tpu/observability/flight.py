"""Engine flight recorder: a lock-cheap ring buffer of scheduler decisions.

The reference operator's observability story is durable state plus events
you can REPLAY after the fact (OTLP trace continuity checkpointed in CR
status, k8s Events as execution history — SURVEY §0). The engine is where
all the interesting scheduling now happens — admit/reserve, chunked prefill,
decode blocks, speculation, preempt/resume, park/adopt, deadline expiry,
shed, crash — but until this module it exposed only aggregate counters:
when something corrupted, diagnosis was re-run archaeology. The flight
recorder keeps the *decisions*, per request, in a fixed-size window:

- ``record(kind, rid=..., slot=..., **detail)`` — one structured event,
  monotonic-stamped and sequence-numbered, appended to a bounded deque.
  Engine-thread callers dominate; ``submit``/shed events arrive from caller
  threads, so appends take one short lock (a few hundred ns — the events
  are at dispatch granularity, never per token). Recording is always-on by
  default and ~zero cost when the engine is idle (no events, no work);
  ``ACP_FLIGHT=0`` (or ``enabled=False``) turns ``record`` into one bool
  branch for bench A/B legs.
- per-request timelines — events carrying a ``rid`` are also indexed by
  request, so ``timeline(rid)`` replays one request's full decision
  sequence even after the global window rolled past it; finished timelines
  stay queryable in a small LRU.
- phase attribution — ``attribute_phases`` derives ``queue_wait`` /
  ``prefill`` / ``decode`` / ``preempt_stall`` / ``tool_overlap_hidden``
  windows from the event stream; ``finish`` exports them as
  ``acp_engine_phase_seconds{phase=...}`` windowed histograms and — when a
  tracer and the request's trace context are wired — as OTLP child spans
  under the Task's existing trace, so engine internals finally appear in
  the same waterfall the controller already starts.
- crash dumps — ``dump_crash`` snapshots the last-N events +
  ``Engine.stats()`` + the paged allocator audit to a JSON file under
  ``$ACP_FLIGHT_DUMP_DIR`` (default off) right before the engine loop's
  loud crash; ``faults.py``'s ``engine.invariant_break`` site proves the
  path end to end.

Cross-thread contract: reads (``events`` / ``timeline`` / ``stats``) run on
REST scrape threads and take the same lock as ``record`` — enforced by the
acplint thread-ownership pass (the read methods are declared
``# acp: cross-thread``; see analysis/passes/thread_ownership.py, which
also bans server code from reaching recorder privates directly).
"""

from __future__ import annotations

import collections
import json
import logging
import os
import threading
import time
from typing import Any, Optional

from .metrics import REGISTRY

log = logging.getLogger("acp_tpu.flight")

DEFAULT_CAPACITY = 4096
PER_REQUEST_CAP = 512  # events indexed per request (timeline bound)
FINISHED_TIMELINES = 64  # finished request timelines kept for /timeline
# trace export (observability/trace_export.py) replays finished timelines;
# replay-scale runs can finish more requests than the default LRU holds, so
# the cap is env-tunable and evictions are COUNTED (stats()/trace docs flag
# an incomplete export instead of silently truncating it)

# the phase vocabulary exported as acp_engine_phase_seconds{phase=...}
PHASES = (
    "queue_wait", "prefill", "decode", "preempt_stall",
    "tool_overlap_hidden", "host_stall",
)

# event kinds that carry a rid and mark lifecycle edges (documented in
# docs/observability.md "Flight recorder & timelines"):
#   submit shed admit prefill_chunk prefill_done decode_block spec_verify
#   preempt park adopt park_release tool_call expire cancel finish
#   swap_out swap_in prefix_share invariant_violation crash restart
#   cold_compile prewarm_gap (compute efficiency observatory: a compiled-
#   program first-dispatch after prewarm, and a prewarm shape that never
#   formed — see observability/profiler.py)


def _trace_ids(trace) -> Optional[tuple[str, str]]:
    """(trace_id, parent_span_id) from a SpanContext-like object or dict;
    None when there is nothing to parent spans under."""
    if trace is None:
        return None
    if isinstance(trace, dict):
        tid, sid = trace.get("trace_id", ""), trace.get("span_id", "")
    else:
        tid = getattr(trace, "trace_id", "")
        sid = getattr(trace, "span_id", "")
    return (tid, sid) if tid else None


def attribute_phases(
    events: list[dict],
) -> tuple[dict[str, float], list[tuple[str, float, float]]]:
    """Derive per-phase durations AND windows from one request's rendered
    event list. Returns ``(durations, windows)`` where windows are
    ``(phase, t0, t1)`` monotonic intervals (preempt stalls and tool-overlap
    windows may repeat). Durations sum (excluding the decode-overlapping
    ``tool_overlap_hidden``) to ~end-to-end latency:

    - ``queue_wait``     submit -> first admission (slot + pages reserved)
    - ``prefill``        first admission -> first sampled token
    - ``preempt_stall``  each preemption -> the resume's first token (the
      latency the request lost to pool pressure: requeue wait + re-prefill)
    - ``decode``         first token -> finish, minus the preempt stalls
    - ``tool_overlap_hidden``  per early-emitted tool call, emit -> finish
      (the execution window overlap hid inside decode; informational — it
      overlaps ``decode`` rather than extending the total)
    - ``host_stall``  per KV swap event, the engine-thread seconds spent
      blocked inside host<->HBM copies for this request (``stall_s`` on
      ``swap_out``/``swap_in`` events); informational — it overlaps the
      phase the swap ran inside (prefill or preempt_stall) rather than
      extending the total

    Tolerant of partial histories: a request that was shed/expired/crashed
    before some edge simply lacks the later phases."""
    t_submit = t_admit = t_first = t_end = None
    stalls: list[tuple[float, float]] = []
    tool_marks: list[float] = []
    host_stalls: list[tuple[float, float]] = []
    pending_preempt: Optional[float] = None
    for ev in events:
        kind, t = ev["kind"], ev["t"]
        if kind == "submit" and t_submit is None:
            t_submit = t
        elif kind == "admit" and t_admit is None:
            t_admit = t
        elif kind == "prefill_done":
            if t_first is None:
                t_first = t
            if pending_preempt is not None:
                stalls.append((pending_preempt, t))
                pending_preempt = None
        elif kind == "preempt":
            if pending_preempt is None:
                pending_preempt = t
        elif kind == "tool_call":
            tool_marks.append(t)
        elif kind in ("swap_out", "swap_in"):
            stall = float((ev.get("detail") or {}).get("stall_s") or 0.0)
            if stall > 0:
                host_stalls.append((t - stall, t))
        elif kind in ("finish", "expire", "cancel", "shed"):
            t_end = t
    if not events:
        return {}, []
    if t_end is None:
        t_end = events[-1]["t"]
    if pending_preempt is not None:  # preempted, never resumed before end
        stalls.append((pending_preempt, t_end))
    windows: list[tuple[str, float, float]] = []
    if t_submit is not None and t_admit is not None and t_admit > t_submit:
        windows.append(("queue_wait", t_submit, t_admit))
    if t_admit is not None and t_first is not None and t_first > t_admit:
        windows.append(("prefill", t_admit, t_first))
    # stalls are carved out of whichever phase window contains them: a
    # mid-prefill preemption (preempt before the first token) closes at
    # the FIRST prefill_done and lies inside the prefill window; a
    # mid-decode preemption closes at a later resume (or the end) and
    # lies inside decode. Subtracting from the wrong side would zero
    # decode and double-count prefill for mid-prefill victims.
    pre_stall = post_stall = 0.0
    for a, b in stalls:
        if b > a:
            windows.append(("preempt_stall", a, b))
            if t_first is not None and a < t_first:
                pre_stall += b - a
            else:
                post_stall += b - a
    if t_first is not None and t_end > t_first:
        windows.append(("decode", t_first, t_end))
    for tm in tool_marks:
        if t_end > tm:
            windows.append(("tool_overlap_hidden", tm, t_end))
    for a, b in host_stalls:
        if b > a:
            windows.append(("host_stall", a, b))
    durations: dict[str, float] = {}
    for phase, a, b in windows:
        durations[phase] = durations.get(phase, 0.0) + (b - a)
    if "prefill" in durations:
        durations["prefill"] = max(0.0, durations["prefill"] - pre_stall)
    if "decode" in durations:
        durations["decode"] = max(0.0, durations["decode"] - post_stall)
    return durations, windows


class FlightRecorder:
    """Fixed-size, always-on event window over the engine's decisions.

    One recorder per :class:`~agentcontrolplane_tpu.engine.engine.Engine`
    (``engine.flight``). ``tracer`` (optional, wired by the operator) turns
    finished requests' phase windows into OTLP child spans."""

    def __init__(
        self,
        capacity: Optional[int] = None,
        enabled: Optional[bool] = None,
        per_request_cap: int = PER_REQUEST_CAP,
        finished_timelines: Optional[int] = None,
    ):
        if capacity is None:
            capacity = int(os.environ.get("ACP_FLIGHT_EVENTS", str(DEFAULT_CAPACITY)))
        if finished_timelines is None:
            finished_timelines = int(
                os.environ.get("ACP_FLIGHT_TIMELINES", str(FINISHED_TIMELINES))
            )
        if enabled is None:
            enabled = os.environ.get("ACP_FLIGHT", "1") not in ("", "0")
        self.enabled = bool(enabled)
        self.capacity = max(16, int(capacity))
        self.per_request_cap = max(8, int(per_request_cap))
        # OTLP linkage: a tracing.Tracer (or None). Assigned post-init by
        # whoever owns a tracer (Operator.start); plain attribute replacement.
        self.tracer = None
        self._lock = threading.Lock()
        self._events: "collections.deque[tuple]" = collections.deque(
            maxlen=self.capacity
        )
        self._seq = 0
        self._recorded = 0  # total ever recorded (window may have dropped)
        self._by_rid: dict[str, list] = {}  # live request -> its events
        self._truncated_rids: set[str] = set()  # per-request cap hit
        self._done: "collections.OrderedDict[str, list]" = collections.OrderedDict()
        self._done_cap = max(1, int(finished_timelines))
        self._evicted_timelines = 0  # finished timelines aged out of the LRU
        # monotonic -> wall clock, for span export and dump timestamps
        self._mono_to_wall = time.time() - time.monotonic()

    # -- write side (engine thread + submit threads) ----------------------

    def record(self, kind: str, rid: Optional[str] = None, slot: int = -1, **detail) -> None:
        """Append one event. Lock-cheap; safe from any thread."""
        if not self.enabled:
            return
        t = time.monotonic()
        with self._lock:
            self._seq += 1
            ev = (self._seq, t, kind, rid, slot, detail or None)
            self._events.append(ev)
            self._recorded += 1
            if rid is not None:
                lst = self._by_rid.get(rid)
                if lst is None:
                    lst = self._by_rid[rid] = []
                if len(lst) < self.per_request_cap:
                    lst.append(ev)
                elif rid not in self._truncated_rids:
                    self._truncated_rids.add(rid)

    def finish(
        self,
        rid: str,
        reason: str,
        slot: int = -1,
        trace=None,
        **detail,
    ) -> dict[str, float]:
        """Record the request's terminal event, derive its phase
        attribution, export ``acp_engine_phase_seconds`` histograms (and
        OTLP child spans when a tracer + trace context are present), and
        retire the timeline into the finished LRU. Returns the phase
        durations (seconds). Engine-thread."""
        if not self.enabled:
            return {}
        self.record("finish", rid=rid, slot=slot, reason=reason, **detail)
        with self._lock:
            events = self._by_rid.pop(rid, None)
            truncated = rid in self._truncated_rids
            self._truncated_rids.discard(rid)
            if events is not None:
                self._retire_locked(rid, events)
        if not events:
            return {}
        rendered = [self._render(e) for e in events]
        durations, windows = attribute_phases(rendered)
        for phase, dur in durations.items():
            REGISTRY.observe(
                "acp_engine_phase_seconds",
                dur,
                labels={"phase": phase},
                help="per-request engine phase latency attribution derived "
                "from the flight recorder (queue_wait | prefill | decode | "
                "preempt_stall | tool_overlap_hidden | host_stall)",
            )
        if truncated:
            log.debug("flight timeline for rid %s truncated at %d events",
                      rid, self.per_request_cap)
        self._export_spans(rid, windows, trace)
        return durations

    def _retire_locked(self, rid: str, events: list) -> None:
        """Move a live timeline into the finished LRU (hold ``_lock``). A
        rid retired twice (a terminal race recording one more event after
        the first retire) EXTENDS its finished timeline rather than
        clobbering it."""
        prior = self._done.pop(rid, None)
        self._done[rid] = (prior + events) if prior else events
        while len(self._done) > self._done_cap:
            self._done.popitem(last=False)
            self._evicted_timelines += 1

    def discard(self, rid: str) -> None:
        """Retire a timeline without phase export (shed before admission,
        follower replays, bulk drains)."""
        with self._lock:
            events = self._by_rid.pop(rid, None)
            self._truncated_rids.discard(rid)
            if events:
                self._retire_locked(rid, events)

    def discard_live(self) -> None:
        """Drop every live timeline (engine thread exit / crash drain) —
        the global window keeps the raw events for the crash dump."""
        with self._lock:
            self._by_rid.clear()
            self._truncated_rids.clear()

    # -- span export ------------------------------------------------------

    def _export_spans(self, rid: str, windows, trace) -> None:
        tracer = self.tracer
        ids = _trace_ids(trace)
        if tracer is None or ids is None or not windows:
            return
        trace_id, parent_id = ids
        off = self._mono_to_wall
        try:
            from .tracing import Span, new_span_id

            for phase, a, b in windows:
                span = Span(
                    name=f"engine.{phase}",
                    trace_id=trace_id,
                    span_id=new_span_id(),
                    parent_span_id=parent_id,
                    start_time=a + off,
                    attributes={"request_id": rid, "phase": phase},
                )
                tracer.end_span(span, end_time=b + off)
        except Exception:  # tracing must never take the engine down
            log.exception("flight span export failed for rid %s", rid)

    # -- read side (REST scrape threads) ----------------------------------

    @staticmethod
    def _render(ev: tuple) -> dict[str, Any]:  # acp: cross-thread (pure)
        seq, t, kind, rid, slot, detail = ev
        out: dict[str, Any] = {"seq": seq, "t": round(t, 6), "kind": kind}
        if rid is not None:
            out["rid"] = rid
        if slot >= 0:
            out["slot"] = slot
        if detail:
            out["detail"] = detail
        return out

    def events(  # acp: cross-thread
        self,
        last: int = 200,
        kind: Optional[str] = None,
        rid: Optional[str] = None,
    ) -> list[dict[str, Any]]:
        """The newest ``last`` window events (oldest first), optionally
        filtered by kind and/or rid."""
        with self._lock:
            evs = list(self._events)
        if kind is not None:
            evs = [e for e in evs if e[2] == kind]
        if rid is not None:
            evs = [e for e in evs if e[3] == rid]
        if last > 0:
            evs = evs[-last:]
        return [self._render(e) for e in evs]

    def timeline(self, rid: str) -> Optional[list[dict[str, Any]]]:  # acp: cross-thread
        """One request's full event sequence (live or recently finished);
        None when the request is unknown (never recorded, or its timeline
        aged out of the finished LRU)."""
        with self._lock:
            lst = self._by_rid.get(rid)
            if lst is None:
                lst = self._done.get(rid)
            lst = list(lst) if lst is not None else None
        if lst is None:
            return None
        return [self._render(e) for e in lst]

    def timeline_doc(self, rid: str) -> Optional[dict[str, Any]]:  # acp: cross-thread
        """Timeline + phase attribution, the /v1/requests/{id}/timeline
        payload: events with window-relative offsets, per-phase durations,
        and the end-to-end total they sum to."""
        events = self.timeline(rid)
        if events is None:
            return None
        durations, windows = attribute_phases(events)
        t0 = events[0]["t"] if events else 0.0
        doc = {
            "request_id": rid,
            "events": [{**e, "t_rel": round(e["t"] - t0, 6)} for e in events],
            "phases": {k: round(v, 6) for k, v in durations.items()},
            "phase_windows": [
                {"phase": p, "start_rel": round(a - t0, 6), "end_rel": round(b - t0, 6)}
                for p, a, b in windows
            ],
            "total_s": round(events[-1]["t"] - t0, 6) if events else 0.0,
        }
        plan = _rate_plan_summary(events)
        if plan is not None:
            doc["rate_plan"] = plan
        return doc

    def timelines(self) -> dict[str, list[dict[str, Any]]]:  # acp: cross-thread
        """Every queryable per-request timeline (finished LRU first, then
        live), rendered — the trace-export read surface. Timelines survive
        the global event window rolling (``_by_rid``/``_done`` are indexed
        separately from the deque); what bounds them is the finished LRU,
        whose evictions ``stats()['evicted_timelines']`` counts."""
        with self._lock:
            snap = [(rid, list(evs)) for rid, evs in self._done.items()]
            snap += [(rid, list(evs)) for rid, evs in self._by_rid.items()]
        return {rid: [self._render(e) for e in evs] for rid, evs in snap}

    def truncated_rids(self) -> set[str]:  # acp: cross-thread
        """Live rids whose timelines hit ``per_request_cap`` (trace export
        marks these rows rather than exporting a silently short timeline)."""
        with self._lock:
            return set(self._truncated_rids)

    def request_ids(self, last: int = 32) -> list[str]:  # acp: cross-thread
        """Recently finished + live request ids with queryable timelines
        (newest finished last) — the CLI's discovery surface."""
        with self._lock:
            done = list(self._done.keys())
            live = list(self._by_rid.keys())
        return (done + live)[-last:]

    def stats(self) -> dict[str, Any]:  # acp: cross-thread
        with self._lock:
            return {
                "enabled": self.enabled,
                "capacity": self.capacity,
                "window_events": len(self._events),
                "recorded_total": self._recorded,
                "live_requests": len(self._by_rid),
                "finished_timelines": len(self._done),
                "finished_timeline_cap": self._done_cap,
                "evicted_timelines": self._evicted_timelines,
            }

    # -- crash dumps ------------------------------------------------------

    def dump_crash(self, engine, error: BaseException) -> Optional[str]:
        """Snapshot the recent window + engine stats + allocator audit to a
        JSON file under ``$ACP_FLIGHT_DUMP_DIR`` (default off — unset means
        no dump). Called from the engine loop's crash handler BEFORE futures
        are failed; best-effort, never masks the crash. Returns the path."""
        dump_dir = os.environ.get("ACP_FLIGHT_DUMP_DIR", "")
        if not dump_dir:
            return None
        try:
            doc: dict[str, Any] = {
                "error": {"type": type(error).__name__, "message": str(error)},
                "wall_time": time.strftime(
                    "%Y-%m-%dT%H:%M:%SZ",
                    time.gmtime(time.monotonic() + self._mono_to_wall),
                ),
                "events": self.events(last=self.capacity),
                "flight": self.stats(),
            }
            try:
                doc["engine_stats"] = engine.stats()
            except Exception as e:  # corrupt state may break stats itself
                doc["engine_stats"] = {"error": repr(e)}
            allocator = getattr(engine, "_allocator", None)
            if allocator is not None:
                try:
                    free_pages, refs = allocator.audit()
                    doc["allocator_audit"] = {
                        "free": len(free_pages),
                        "referenced": len(refs),
                        "refcounts": {str(pg): n for pg, n in sorted(refs.items())},
                    }
                except Exception as e:
                    doc["allocator_audit"] = {"error": repr(e)}
            os.makedirs(dump_dir, exist_ok=True)
            path = os.path.join(
                dump_dir, f"flightdump-{int(time.time() * 1e3)}-{os.getpid()}.json"
            )
            with open(path, "w") as f:
                json.dump(doc, f, indent=2)
                f.write("\n")
            log.error("engine crash dump written to %s", path)
            return path
        except Exception:
            log.exception("crash dump failed (crash itself is re-raised)")
            return None


def _rate_plan_summary(events: list) -> Optional[dict[str, Any]]:
    """Quota-vs-actual for one request's chunk-rate plan (engine/planner.py):
    from its ``quota`` projection events (admission + reprojections) and
    the ``prefill_chunk`` dispatches that followed, derive what the
    planner asked for per cycle and what the scheduler actually delivered.
    None when the request carried no rate plan (planner off, no chunked
    prefill, or the timeline predates PR 13)."""
    quotas = [e for e in events if e["kind"] == "quota"]
    if not quotas:
        return None
    chunks = [e for e in events if e["kind"] == "prefill_chunk"]
    tokens = sum(int(e["detail"].get("n", 0)) for e in chunks)
    span = (
        (chunks[-1]["t"] - quotas[0]["t"]) if chunks else 0.0
    )
    return {
        "quota": quotas[-1]["detail"].get("quota"),
        "projections": [
            {
                "reason": e["detail"].get("reason"),
                "quota": e["detail"].get("quota"),
                "tokens_left": e["detail"].get("tokens_left"),
                "seconds_left": e["detail"].get("seconds_left"),
            }
            for e in quotas
        ],
        "reprojections": sum(
            1 for e in quotas if e["detail"].get("reason") != "admit"
        ),
        "chunks_dispatched": len(chunks),
        "chunk_tokens": tokens,
        "prefill_span_s": round(max(0.0, span), 6),
    }


def phase_summaries() -> dict[str, dict[str, float]]:
    """p50/p99 of the windowed ``acp_engine_phase_seconds`` histograms per
    phase — a convenience for status payloads and tests."""
    out: dict[str, dict[str, float]] = {}
    for phase in PHASES:
        labels = {"phase": phase}
        count, window = REGISTRY.series_window("acp_engine_phase_seconds", labels)
        if not count:
            continue
        out[phase] = {
            "count": count,
            "p50": REGISTRY.quantile("acp_engine_phase_seconds", 0.5, labels),
            "p99": REGISTRY.quantile("acp_engine_phase_seconds", 0.99, labels),
        }
    return out


__all__ = [
    "FlightRecorder",
    "attribute_phases",
    "phase_summaries",
    "PHASES",
]
