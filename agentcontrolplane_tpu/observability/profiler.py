"""Compute efficiency observatory: per-dispatch program telemetry.

The flight recorder (observability/flight.py) made the *scheduler's*
decisions inspectable; this module is its compute-side twin. The engine
dispatches a zoo of compiled programs — prefill buckets, chunk
continuations, decode widths, spec verify, swap restores, prefix copies —
and until this module nobody could answer "where does device time go, how
much of each dispatch is padding, and how many computed tokens were thrown
away?". Three layers, all hanging off one :class:`DispatchProfiler` owned
by the engine (``engine.profiler``):

- **per-program dispatch telemetry** — every device-dispatch site wraps its
  jit call in ``t0 = profiler.start()`` / ``profiler.record(key, t0, ...)``
  where ``key`` names the compiled program the way the jit cache keys it
  (kind × bucket/width × batch × layout, plus a ``+tbl`` marker for
  programs whose trace shape changes once the grammar token table exists).
  ``record`` accumulates host dispatch wall time, real-vs-padded token and
  slot counts, and — SAMPLED, every ``sample_every``-th dispatch per
  program, to bound overhead — a ``jax.block_until_ready`` device-inclusive
  time. Each dispatch also lands one ``acp_engine_dispatch_seconds
  {program=}`` observation (dispatch granularity, never per token: the same
  always-on-cheap posture as the flight recorder; ``ACP_PROF=0`` reduces
  every hook to one bool branch for bench A/B).

- **cold-compile observatory** — the FIRST dispatch of a program key is
  where jit traces and compiles, so its wall time is recorded as that
  program's compile cost (the first dispatch always blocks, so the number
  is the real stall, not the async enqueue). Once the engine declares
  prewarm complete (:meth:`mark_prewarmed`), any further first-dispatch is
  a compile REAL TRAFFIC paid for — a serving-time latency bug. It records
  a ``cold_compile`` flight event and increments
  ``acp_engine_cold_compiles_total``, turning the silent "prewarm: batch
  never formed" log line into an alertable signal.

- **goodput/waste accounting** — dispatch sites classify every computed
  token position into exactly one cause via :meth:`account`: ``goodput``
  (prompt rows prefilled into live KV + sampled tokens committed), or a
  waste cause (``pad_bucket`` prefill bucket padding, ``pad_width`` decode/
  verify lane+step padding, ``spec_rejected`` rejected draft positions,
  ``preempt_discard`` discarded-and-recomputed KV, ``swap_recompute``
  host-swap-error recompute, ``dedup_rewind`` follower rewinds,
  ``prewarm`` synthetic warm-up traffic, ``pad_fuse`` the pow2 padding
  rows the fused megastep adds over the split path's exact pow2
  decomposition — the fused-program waste row). :meth:`reclassify` moves already-
  counted goodput into a waste cause when the engine later discards it
  (zero-sum, clamped), so conservation — ``computed == goodput + Σ waste``
  — holds by construction and is audited by the armed invariant checker
  (engine/invariants.py ``_verify_profiler``). Exported as
  ``acp_engine_tokens_computed_total{cause=}`` plus the
  ``acp_engine_goodput_ratio`` gauge.

Cross-thread contract: the write side (``record``/``account``/
``reclassify``) runs on the engine thread; the read side (``stats`` /
``ledger`` / ``publish``) runs on REST scrape threads and takes the same
lock — enforced by the acplint thread-ownership pass (read methods are
declared ``# acp: cross-thread``; server code must go through them, never
the profiler's privates).
"""

from __future__ import annotations

import collections
import os
import threading
import time
from typing import Any, Optional

from .metrics import REGISTRY

# every computed token position lands in goodput or exactly one of these
WASTE_CAUSES = (
    "pad_bucket",       # prefill rows padded to the compiled bucket
    "pad_width",        # decode/verify lanes+steps beyond committed tokens
    "spec_rejected",    # draft positions the verify pass rejected
    "preempt_discard",  # KV discarded at preempt/expiry and recomputed
    "swap_recompute",   # host-tier restore failed; preserved KV recomputed
    "dedup_rewind",     # follower rewound past rows its dead leader wrote
    "prewarm",          # synthetic warm-up traffic (compute, no serving)
    "pad_fuse",         # pow2 padding rows the fused megastep adds (the
                        # split path's pow2 DECOMPOSITION has none): the
                        # compute price paid for one-dispatch cycles
)

COLD_EVENTS_KEPT = 32  # recent serving-time cold compiles kept for /perf


class _Program:
    """Mutable per-program aggregate (guarded by the profiler lock)."""

    __slots__ = (
        "dispatches", "host_s", "blocked_s", "blocked_samples",
        "real_tokens", "padded_tokens", "real_slots", "padded_slots",
        "first_wall_s", "cold",
    )

    def __init__(self) -> None:
        self.dispatches = 0
        self.host_s = 0.0
        self.blocked_s = 0.0      # sampled dispatch-to-ready wall time
        self.blocked_samples = 0
        self.real_tokens = 0
        self.padded_tokens = 0
        self.real_slots = 0
        self.padded_slots = 0
        self.first_wall_s = 0.0   # first dispatch = trace + compile wall
        self.cold = False         # first dispatch landed AFTER prewarm


class DispatchProfiler:
    """Per-dispatch program telemetry + cold-compile tracking + goodput
    ledger. One per :class:`~agentcontrolplane_tpu.engine.engine.Engine`
    (``engine.profiler``); ``flight`` (optional) receives ``cold_compile``
    events so serving-time compiles appear inline with the scheduler
    decisions that caused them."""

    # A/B caveat: `enabled` is a plain mutable attribute (benches toggle it
    # on a live engine). A program whose FIRST dispatch lands inside a
    # disabled window is never registered, so it would read as a cold
    # compile when re-enabled after mark_prewarmed() — toggle only on
    # warmed engines whose program zoo is already registered (the shipped
    # bench fixture runs its profiler-on warm-up leg first for exactly
    # this reason), or re-baseline with a fresh profiler.

    def __init__(
        self,
        flight=None,
        enabled: Optional[bool] = None,
        sample_every: Optional[int] = None,
    ):
        if enabled is None:
            enabled = os.environ.get("ACP_PROF", "1") not in ("", "0")
        if sample_every is None:
            sample_every = int(os.environ.get("ACP_PROF_SAMPLE", "32"))
        self.enabled = bool(enabled)
        self.sample_every = max(1, int(sample_every))
        self._flight = flight
        self._lock = threading.Lock()
        self._programs: dict[str, _Program] = {}
        self._warm = False
        self._cold_serving = 0
        self._cold_events: "collections.deque[dict]" = collections.deque(
            maxlen=COLD_EVENTS_KEPT
        )
        # the goodput/waste ledger: computed == goodput + sum(waste) holds
        # by construction (account() adds both sides; reclassify() is a
        # clamped zero-sum move) — the armed invariant checker audits it
        self._computed = 0
        self._goodput = 0
        self._waste: dict[str, int] = {c: 0 for c in WASTE_CAUSES}
        # registry values pushed so far, so publish() emits deltas and two
        # concurrent publishers can't double-count
        self._pub_tokens: dict[str, int] = {}
        self._pub_prog: dict[tuple[str, str], int] = {}

    # -- write side (engine thread) ---------------------------------------

    def start(self) -> float:
        """Stamp a dispatch about to be issued (0.0 when disabled — the
        matching ``record`` is then skipped by its own guard)."""
        return time.monotonic() if self.enabled else 0.0

    def record(
        self,
        key: str,
        t0: float,
        out: Any = None,
        real_tokens: int = 0,
        padded_tokens: int = 0,
        real_slots: int = 0,
        padded_slots: int = 0,
    ) -> None:
        """One dispatch of compiled program ``key``: host wall time since
        ``t0`` plus real/padded token+slot counts. ``out`` (any jax value
        the dispatch produced) lets the sampled legs — and always the FIRST
        dispatch of a key, whose wall time is the compile cost — block
        until device-ready for a device-inclusive time. Sampling bounds the
        overhead; blocking changes timing only, never values, so profiler
        on/off stays byte-identical."""
        if not self.enabled or not t0:
            # t0 == 0.0 means start() ran while the profiler was disabled
            # and `enabled` flipped mid-dispatch (bench A/B legs toggle it
            # from another thread) — a time-since-boot "duration" from the
            # zero stamp would corrupt the program's stats
            return
        host_s = time.monotonic() - t0
        with self._lock:
            p = self._programs.get(key)
            first = p is None
            if first:
                p = self._programs[key] = _Program()
            sample = first or (p.dispatches % self.sample_every == 0)
        blocked_s = None
        if sample and out is not None:
            import jax

            jax.block_until_ready(out)
            blocked_s = time.monotonic() - t0
        cold = False
        wall = blocked_s if blocked_s is not None else host_s
        with self._lock:
            p.dispatches += 1
            p.host_s += host_s
            p.real_tokens += int(real_tokens)
            p.padded_tokens += int(padded_tokens)
            p.real_slots += int(real_slots)
            p.padded_slots += int(padded_slots)
            if blocked_s is not None:
                p.blocked_s += blocked_s
                p.blocked_samples += 1
            if first:
                p.first_wall_s = wall
                if self._warm:
                    p.cold = True
                    self._cold_serving += 1
                    self._cold_events.append(
                        {"program": key, "wall_s": round(wall, 6),
                         "t": round(t0, 6)}
                    )
                    cold = True
        REGISTRY.observe(
            "acp_engine_dispatch_seconds", host_s, labels={"program": key},
            help="host wall time per device dispatch, by compiled program "
            "(kind x bucket/width x batch x layout); sampled legs include "
            "block_until_ready device time in the per-program stats",
        )
        if cold:
            REGISTRY.counter_add(
                "acp_engine_cold_compiles_total", 1.0,
                help="first-dispatch-of-shape events AFTER prewarm declared "
                "completion — compiles real traffic paid for at serving "
                "time (each is a latency bug: widen prewarm coverage)",
            )
            if self._flight is not None:
                self._flight.record(
                    "cold_compile", program=key, wall_s=round(wall, 6)
                )

    def account(self, goodput: int = 0, **waste: int) -> None:
        """Classify one dispatch's computed token positions: ``goodput``
        plus any :data:`WASTE_CAUSES` keywords. The computed total is the
        sum of what the caller passes, so ledger conservation holds by
        construction; an unknown cause raises (programming error)."""
        if not self.enabled:
            return
        with self._lock:
            total = int(goodput)
            self._goodput += int(goodput)
            for cause, n in waste.items():
                if cause not in self._waste:
                    raise KeyError(f"unknown waste cause {cause!r}")
                if n:
                    self._waste[cause] += int(n)
                    total += int(n)
            self._computed += total

    def reclassify(self, cause: str, n: int) -> None:
        """Move ``n`` already-goodput token positions into ``cause`` — the
        engine discarded compute it had counted useful (preemption without
        a host swap, a failed restore, a dedup follower rewind). Zero-sum
        and clamped at the available goodput, so conservation survives
        over-estimates (e.g. prefix-cache rows that were never computed in
        this admission)."""
        if not self.enabled or n <= 0:
            return
        if cause not in self._waste:
            raise KeyError(f"unknown waste cause {cause!r}")
        with self._lock:
            n = min(int(n), self._goodput)
            if n <= 0:
                return
            self._goodput -= n
            self._waste[cause] += n

    def mark_prewarmed(self) -> None:
        """Prewarm coverage is complete: every LATER first-dispatch of a
        program key is a serving-time cold compile (flight event +
        ``acp_engine_cold_compiles_total``)."""
        with self._lock:
            self._warm = True

    # -- read side (engine loop per cycle + REST scrape threads) ----------

    def publish(self) -> None:  # acp: cross-thread
        """Push ledger counters (as deltas) and the goodput-ratio gauge to
        the registry. Called per scheduler cycle by the engine loop and at
        scrape time; safe from any thread (delta bookkeeping happens under
        the profiler lock, so concurrent publishers never double-count)."""
        if not self.enabled:
            return
        with self._lock:
            token_deltas: list[tuple[str, int]] = []
            for cause, n in [("goodput", self._goodput), *self._waste.items()]:
                d = n - self._pub_tokens.get(cause, 0)
                if d:
                    token_deltas.append((cause, d))
                    self._pub_tokens[cause] = n
            prog_deltas: list[tuple[str, str, int]] = []
            for key, p in self._programs.items():
                for kind, n in (("real", p.real_tokens), ("padded", p.padded_tokens)):
                    d = n - self._pub_prog.get((key, kind), 0)
                    if d:
                        prog_deltas.append((key, kind, d))
                        self._pub_prog[(key, kind)] = n
            computed, goodput = self._computed, self._goodput
        for cause, d in token_deltas:
            REGISTRY.counter_add(
                "acp_engine_tokens_computed_total", float(d),
                labels={"cause": cause},
                help="computed token positions by outcome: goodput (live KV "
                "+ committed tokens) vs the waste causes (bucket/width "
                "padding, rejected drafts, preempt-discarded KV, host-swap "
                "recompute, dedup rewinds, prewarm)",
            )
        for key, kind, d in prog_deltas:
            REGISTRY.counter_add(
                "acp_engine_dispatch_tokens_total", float(d),
                labels={"program": key, "kind": kind},
                help="token positions dispatched per compiled program, "
                "split real vs padding (the per-program padding-waste "
                "series behind the goodput accounting)",
            )
        REGISTRY.gauge_set(
            "acp_engine_goodput_ratio",
            (goodput / computed) if computed else 1.0,
            help="goodput token positions / all computed token positions "
            "(1.0 = no padding or discarded compute); see "
            "acp_engine_tokens_computed_total for the waste attribution",
        )

    def ledger(self) -> dict[str, Any]:  # acp: cross-thread
        """Snapshot of the goodput/waste ledger (the invariant checker's
        conservation input): ``computed == goodput + sum(waste.values())``."""
        with self._lock:
            return {
                "computed": self._computed,
                "goodput": self._goodput,
                "waste": dict(self._waste),
            }

    def stats(self) -> dict[str, Any]:  # acp: cross-thread
        """The /v1/engine/perf payload: per-program dispatch stats, the
        cold-compile observatory, and the goodput/waste ledger."""
        self.publish()
        with self._lock:
            programs: dict[str, dict[str, Any]] = {}
            for key, p in sorted(
                self._programs.items(), key=lambda kv: -kv[1].host_s
            ):
                if not p.dispatches:
                    # record() creates the entry, drops the lock for the
                    # sampled block_until_ready, then increments — a scrape
                    # landing in that window skips the half-born program
                    continue
                padded_pct = (
                    round(100.0 * p.padded_tokens / (p.real_tokens + p.padded_tokens), 2)
                    if (p.real_tokens + p.padded_tokens) else 0.0
                )
                programs[key] = {
                    "dispatches": p.dispatches,
                    "host_ms_total": round(p.host_s * 1e3, 3),
                    "host_ms_mean": round(p.host_s / p.dispatches * 1e3, 4),
                    "device_ms_mean": (
                        round(p.blocked_s / p.blocked_samples * 1e3, 4)
                        if p.blocked_samples else None
                    ),
                    "device_samples": p.blocked_samples,
                    "real_tokens": p.real_tokens,
                    "padded_tokens": p.padded_tokens,
                    "padding_pct": padded_pct,
                    "real_slots": p.real_slots,
                    "padded_slots": p.padded_slots,
                    "first_wall_ms": round(p.first_wall_s * 1e3, 3),
                    "cold": p.cold,
                }
            waste = dict(self._waste)
            computed, goodput = self._computed, self._goodput
            doc = {
                "enabled": self.enabled,
                "sample_every": self.sample_every,
                "prewarmed": self._warm,
                "programs": programs,
                "cold_compiles": {
                    "serving": self._cold_serving,
                    "events": list(self._cold_events),
                },
                "goodput": {
                    "computed": computed,
                    "goodput": goodput,
                    "ratio": round(goodput / computed, 4) if computed else 1.0,
                    "waste": waste,
                },
            }
        return doc


__all__ = ["DispatchProfiler", "WASTE_CAUSES"]
