"""Metrics registry — Prometheus-text-format counters/gauges/histograms.

The reference exposes controller-runtime's Prometheus metrics server
(``cmd/main.go:167-206``); ours serves this registry at ``/metrics`` on the
REST server, adding engine metrics (tok/s, batch occupancy, KV pages) the
reference has no equivalent for.
"""

from __future__ import annotations

import threading
from collections import deque
from dataclasses import dataclass, field


@dataclass
class _Metric:
    name: str
    help: str
    type: str
    values: dict[tuple[tuple[str, str], ...], float] = field(default_factory=dict)


HIST_WINDOW = 4096  # bounded reservoir per series (quantiles over a window)


class Registry:
    def __init__(self, hist_window: int = HIST_WINDOW):
        self._metrics: dict[str, _Metric] = {}
        self._hist_window = hist_window
        self._hist_data: dict[str, dict[tuple, "deque[float]"]] = {}
        self._hist_count: dict[str, dict[tuple, int]] = {}
        self._hist_sum: dict[str, dict[tuple, float]] = {}
        self._lock = threading.Lock()

    def _get(self, name: str, help: str, type_: str) -> _Metric:
        m = self._metrics.get(name)
        if m is None:
            m = _Metric(name=name, help=help, type=type_)
            self._metrics[name] = m
        return m

    @staticmethod
    def _key(labels: dict[str, str] | None) -> tuple[tuple[str, str], ...]:
        return tuple(sorted((labels or {}).items()))

    def counter_add(self, name: str, value: float = 1.0, labels: dict[str, str] | None = None, help: str = "") -> None:
        with self._lock:
            m = self._get(name, help, "counter")
            k = self._key(labels)
            m.values[k] = m.values.get(k, 0.0) + value

    def gauge_set(self, name: str, value: float, labels: dict[str, str] | None = None, help: str = "") -> None:
        with self._lock:
            m = self._get(name, help, "gauge")
            m.values[self._key(labels)] = value

    def gauge_remove(self, name: str, labels: dict[str, str] | None = None) -> None:
        """Drop one gauge series (cardinality hygiene: a drained kind/phase
        series is zeroed for one scrape, then removed)."""
        with self._lock:
            m = self._metrics.get(name)
            if m is not None:
                m.values.pop(self._key(labels), None)

    def observe(self, name: str, value: float, labels: dict[str, str] | None = None, help: str = "") -> None:
        with self._lock:
            self._get(name, help, "histogram")
            k = self._key(labels)
            series = self._hist_data.setdefault(name, {})
            if k not in series:
                series[k] = deque(maxlen=self._hist_window)
            series[k].append(value)
            counts = self._hist_count.setdefault(name, {})
            counts[k] = counts.get(k, 0) + 1
            sums = self._hist_sum.setdefault(name, {})
            sums[k] = sums.get(k, 0.0) + value

    def series_window(
        self, name: str, labels: dict[str, str] | None = None
    ) -> tuple[int, list[float]]:
        """(total observation count, windowed values) for one histogram
        series, read under the lock. The count is monotonic (unbounded)
        while the window is the bounded reservoir — callers measuring a
        phase snapshot the count before and slice
        ``window[-min(new, len(window)):]`` after."""
        with self._lock:
            k = self._key(labels)
            count = self._hist_count.get(name, {}).get(k, 0)
            window = list(self._hist_data.get(name, {}).get(k, ()))
        return count, window

    def quantile(self, name: str, q: float, labels: dict[str, str] | None = None) -> float:
        with self._lock:
            data = sorted(self._hist_data.get(name, {}).get(self._key(labels), []))
        if not data:
            return 0.0
        idx = min(int(q * len(data)), len(data) - 1)
        return data[idx]

    def render(self) -> str:
        """Prometheus text exposition format."""
        out: list[str] = []
        with self._lock:
            for m in self._metrics.values():
                if m.help:
                    out.append(f"# HELP {m.name} {self._escape_help(m.help)}")
                out.append(f"# TYPE {m.name} {m.type if m.type != 'histogram' else 'summary'}")
                if m.type == "histogram":
                    for k, vals in self._hist_data.get(m.name, {}).items():
                        lbl = self._render_labels(k)
                        svals = sorted(vals)  # windowed quantiles
                        for q in (0.5, 0.9, 0.99):
                            qk = self._render_labels(k + (("quantile", str(q)),))
                            idx = min(int(q * len(svals)), len(svals) - 1)
                            out.append(f"{m.name}{qk} {svals[idx]}")
                        out.append(f"{m.name}_count{lbl} {self._hist_count[m.name][k]}")
                        out.append(f"{m.name}_sum{lbl} {self._hist_sum[m.name][k]}")
                else:
                    for k, v in m.values.items():
                        out.append(f"{m.name}{self._render_labels(k)} {v}")
        return "\n".join(out) + "\n"

    @staticmethod
    def _escape_label_value(value: str) -> str:
        """Prometheus text-format label-value escaping: backslash, double
        quote, and line feed must be escaped or a value like a model name
        containing ``"`` (or a fault label carrying a newline) corrupts the
        whole scrape — every series after it fails to parse."""
        return (
            str(value)
            .replace("\\", "\\\\")
            .replace('"', '\\"')
            .replace("\n", "\\n")
        )

    @staticmethod
    def _escape_help(text: str) -> str:
        """HELP-line escaping per the text format: backslash and line feed
        (a raw newline would split the HELP text into a garbage line)."""
        return str(text).replace("\\", "\\\\").replace("\n", "\\n")

    @classmethod
    def _render_labels(cls, k: tuple[tuple[str, str], ...]) -> str:
        if not k:
            return ""
        inner = ",".join(
            f'{name}="{cls._escape_label_value(value)}"' for name, value in k
        )
        return "{" + inner + "}"

    def snapshot(self) -> list[dict]:
        """Structured dump for programmatic exporters (OTLP metrics)."""
        out: list[dict] = []
        with self._lock:
            for m in self._metrics.values():
                entry: dict = {"name": m.name, "type": m.type, "help": m.help}
                if m.type == "histogram":
                    series = []
                    for k, vals in self._hist_data.get(m.name, {}).items():
                        svals = sorted(vals)
                        series.append(
                            {
                                "labels": dict(k),
                                "count": self._hist_count[m.name][k],
                                "sum": self._hist_sum[m.name][k],
                                "quantiles": {
                                    q: svals[min(int(q * len(svals)), len(svals) - 1)]
                                    for q in (0.5, 0.9, 0.99)
                                }
                                if svals
                                else {},
                            }
                        )
                    entry["series"] = series
                else:
                    entry["series"] = [
                        {"labels": dict(k), "value": v} for k, v in m.values.items()
                    ]
                out.append(entry)
        return out


REGISTRY = Registry()
