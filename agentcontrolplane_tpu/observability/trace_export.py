"""Trace export: anonymized, replayable workload traces from the flight
recorder.

The flight recorder (observability/flight.py) keeps per-request decision
timelines; this module derives the WORKLOAD MODEL from them — when requests
arrived, how long their prompts and outputs were, which persona (prefix-
sharing key) they belonged to, where tool calls landed, which deadlines and
cancels were in play — and serializes it as a versioned JSON trace document
that ``scenarios/replay.py`` can play back deterministically at 1x/10x/100x.

Anonymization is structural, not best-effort: the trace carries NO prompt
or output content, only lengths, monotonic offsets, and 16-hex persona
fingerprints (the same first-64-token hash the fleet router keys affinity
on). The replayer regenerates synthetic prompts from the lengths + persona
keys, so a trace exported from production traffic is safe to commit next to
the scenario library.

Fleet traces (``export_fleet_trace``) stitch each request's legs — the
router's own timeline plus every replica-local timeline it linked via
``engine_rid`` on ``attempt``/``handoff_start`` events — into ONE timeline
per request, with non-final lifecycle edges kind-rewritten to ``handoff_*``
so :func:`~agentcontrolplane_tpu.observability.flight.attribute_phases`
counts ``queue_wait`` exactly once (arrival -> first admission anywhere in
the pool) and the phases sum to ~end-to-end like single-engine timelines.

Export never silently truncates: recorders count finished-timeline LRU
evictions (``ACP_FLIGHT_TIMELINES`` raises the cap) and per-request event-
cap hits, and the trace doc surfaces both under ``flight`` with a
``complete`` verdict.
"""

from __future__ import annotations

from typing import Any, Optional

TRACE_VERSION = 1

_TERMINAL_KINDS = ("finish", "expire", "cancel", "shed")


def _request_row(events: list[dict]) -> Optional[tuple[float, dict[str, Any]]]:
    """One trace row from one request's rendered timeline: ``(t_submit,
    row)``, or None when the timeline has no submit edge (prewarm legs,
    partial histories that start mid-window)."""
    sub = next((e for e in events if e["kind"] == "submit"), None)
    if sub is None:
        return None
    t_submit = float(sub["t"])
    d = sub.get("detail") or {}
    terminal: Optional[dict] = None
    tool_offsets: list[float] = []
    cancel_at: Optional[float] = None
    for e in events:
        kind = e["kind"]
        if kind in _TERMINAL_KINDS:
            terminal = e
            if kind == "cancel" and cancel_at is None:
                cancel_at = float(e["t"]) - t_submit
        elif kind == "tool_call":
            tool_offsets.append(float(e["t"]) - t_submit)
    td = (terminal.get("detail") or {}) if terminal else {}
    finish = "unknown"
    if terminal is not None:
        finish = str(td.get("reason") or terminal["kind"])
    row: dict[str, Any] = {
        "prompt_tokens": int(d.get("prompt_tokens") or 0),
        "output_tokens": int(td.get("tokens") or 0),
        "persona": str(d.get("key") or ""),
        "finish": finish,
    }
    if d.get("timeout_s") is not None:
        row["deadline_s"] = round(float(d["timeout_s"]), 6)
    if cancel_at is not None:
        row["cancel_after_s"] = round(max(0.0, cancel_at), 6)
    if tool_offsets:
        row["tool_calls"] = [
            {"offset_s": round(max(0.0, o), 6)} for o in tool_offsets
        ]
    return t_submit, row


def _personas(rows: list[dict]) -> dict[str, dict[str, Any]]:
    """Persona mix summary. ``prefix_tokens`` is the replayable shared-
    prefix length: requests sharing a persona key share (at least) their
    first min(64, shortest prompt) tokens — that is what the fingerprint
    hashes — so singleton personas get 0 and shared ones get that floor."""
    by_key: dict[str, list[int]] = {}
    for r in rows:
        key = r.get("persona") or ""
        if key:
            by_key.setdefault(key, []).append(int(r["prompt_tokens"]))
    out: dict[str, dict[str, Any]] = {}
    for key, lens in sorted(by_key.items()):
        shared = min(64, min(lens)) if len(lens) > 1 else 0
        out[key] = {"requests": len(lens), "prefix_tokens": shared}
    return out


def _build_doc(
    timelines: dict[str, list[dict]],
    source: str,
    flight_meta: dict[str, Any],
) -> dict[str, Any]:
    stamped = []
    for rid, events in timelines.items():
        got = _request_row(events)
        if got is not None:
            stamped.append(got)
    stamped.sort(key=lambda p: p[0])
    t0 = stamped[0][0] if stamped else 0.0
    rows = []
    for i, (t_submit, row) in enumerate(stamped):
        rows.append({
            "i": i,
            "offset_s": round(max(0.0, t_submit - t0), 6),
            **row,
        })
    complete = (
        int(flight_meta.get("evicted_timelines") or 0) == 0
        and int(flight_meta.get("truncated_rids") or 0) == 0
        and int(flight_meta.get("missing_legs") or 0) == 0
    )
    return {
        "version": TRACE_VERSION,
        "source": source,
        "anonymized": True,
        "complete": complete,
        "span_s": rows[-1]["offset_s"] if rows else 0.0,
        "requests": rows,
        "personas": _personas(rows),
        "faults": [],
        "flight": flight_meta,
    }


def export_trace(recorder) -> dict[str, Any]:
    """The single-engine trace document: every queryable timeline in
    ``recorder`` (finished LRU + live) becomes one anonymized request row."""
    timelines = recorder.timelines()
    stats = recorder.stats()
    meta = {
        "evicted_timelines": int(stats.get("evicted_timelines") or 0),
        "truncated_rids": len(recorder.truncated_rids()),
        "missing_legs": 0,
    }
    return _build_doc(timelines, "engine", meta)


# -- fleet stitching ---------------------------------------------------------


def stitch_timelines(
    legs: list[tuple[str, list[dict]]],
) -> list[dict[str, Any]]:
    """Merge one request's legs into a single attribution-safe timeline.

    ``legs`` is ``[(role, rendered_events)]`` with roles ``origin`` (the
    router's own timeline), ``attempt`` (a decode / failover leg), and
    ``prefill`` (a disaggregation prefill probe). Events merge in monotonic
    order (all recorders share one in-process clock), then lifecycle edges
    are kind-rewritten so ``attribute_phases`` sees exactly one request:

    - the globally earliest ``submit`` / ``admit`` survive; later ones
      become ``handoff_submit`` / ``handoff_admit`` (a decode replica's own
      queue wait after a handoff is transfer latency inside ``prefill``,
      not a second ``queue_wait`` — the double-count this rewrite fixes)
    - ``prefill_done`` on a ``prefill`` leg becomes
      ``handoff_prefill_done``: the probe's sampled token is not the
      caller-visible first token, the decode leg's is
    - only the globally LAST terminal (``finish``/``expire``/``cancel``/
      ``shed``) survives; earlier ones (the prefill probe's ``finish``, a
      crashed attempt's terminal) become ``handoff_<kind>``

    Unknown kinds pass through untouched and ``attribute_phases`` ignores
    them, so the stitched timeline stays a superset of every leg."""
    merged: list[tuple[str, dict[str, Any]]] = []
    for role, events in legs:
        for ev in events or []:
            merged.append((role, dict(ev)))
    merged.sort(key=lambda p: (float(p[1].get("t", 0.0)), int(p[1].get("seq", 0))))
    first_submit = first_admit = last_terminal = None
    for idx, (_, ev) in enumerate(merged):
        kind = ev["kind"]
        if kind == "submit" and first_submit is None:
            first_submit = idx
        elif kind == "admit" and first_admit is None:
            first_admit = idx
        elif kind in _TERMINAL_KINDS:
            last_terminal = idx
    out: list[dict[str, Any]] = []
    for idx, (role, ev) in enumerate(merged):
        kind = ev["kind"]
        if kind == "submit" and idx != first_submit:
            ev["kind"] = "handoff_submit"
        elif kind == "admit" and idx != first_admit:
            ev["kind"] = "handoff_admit"
        elif kind == "prefill_done" and role == "prefill":
            ev["kind"] = "handoff_prefill_done"
        elif kind in _TERMINAL_KINDS and idx != last_terminal:
            ev["kind"] = f"handoff_{kind}"
        ev["seq"] = idx + 1
        out.append(ev)
    return out


def fleet_request_legs(
    router, rid: str, events: list[dict]
) -> tuple[list[tuple[str, list[dict]]], int]:
    """``(legs, missing)`` for one router-level request: the router's own
    timeline plus each replica-local leg it linked (``engine_rid`` on
    ``attempt`` / ``handoff_start`` events). ``missing`` counts linked legs
    whose replica timeline already aged out of that recorder's LRU."""
    legs: list[tuple[str, list[dict]]] = [("origin", events)]
    missing = 0
    recorders = {
        r.id: getattr(r.engine, "flight", None) for r in router.pool.replicas()
    }
    for ev in events:
        d = ev.get("detail") or {}
        engine_rid = d.get("engine_rid")
        if not engine_rid:
            continue
        if ev["kind"] == "attempt":
            role, replica_id = "attempt", d.get("replica")
        elif ev["kind"] == "handoff_start":
            role, replica_id = "prefill", d.get("prefill")
        else:
            continue
        rec = recorders.get(replica_id)
        leg = rec.timeline(engine_rid) if rec is not None else None
        if leg:
            legs.append((role, leg))
        else:
            missing += 1
    return legs, missing


def stitched_fleet_timelines(router) -> tuple[dict[str, list[dict]], int]:
    """``({rid: stitched_events}, missing_legs)`` across every request the
    router's recorder still holds."""
    out: dict[str, list[dict]] = {}
    missing_total = 0
    for rid, events in router.flight.timelines().items():
        legs, missing = fleet_request_legs(router, rid, events)
        missing_total += missing
        out[rid] = stitch_timelines(legs)
    return out, missing_total


def export_fleet_trace(router) -> dict[str, Any]:
    """The fleet trace document: one row per ROUTER request, derived from
    the stitched cross-replica timeline, so a request that crossed a
    prefill handoff or a failover appears once with end-to-end phases."""
    timelines, missing = stitched_fleet_timelines(router)
    evicted = int(router.flight.stats().get("evicted_timelines") or 0)
    truncated = len(router.flight.truncated_rids())
    for r in router.pool.replicas():
        rec = getattr(r.engine, "flight", None)
        if rec is None:
            continue
        evicted += int(rec.stats().get("evicted_timelines") or 0)
        truncated += len(rec.truncated_rids())
    meta = {
        "evicted_timelines": evicted,
        "truncated_rids": truncated,
        "missing_legs": missing,
    }
    return _build_doc(timelines, "fleet", meta)


def validate_trace(doc: Any) -> list[str]:
    """Structural problems with a trace document (empty list = loadable by
    the replayer). Checked by ``acp-tpu replay`` and the scenario tests —
    a trace is an interchange format, so failures name fields, not code."""
    problems: list[str] = []
    if not isinstance(doc, dict):
        return ["trace is not a JSON object"]
    if doc.get("version") != TRACE_VERSION:
        problems.append(
            f"version {doc.get('version')!r} != supported {TRACE_VERSION}"
        )
    reqs = doc.get("requests")
    if not isinstance(reqs, list):
        return problems + ["requests is not a list"]
    last_off = -1.0
    for i, row in enumerate(reqs):
        if not isinstance(row, dict):
            problems.append(f"requests[{i}] is not an object")
            continue
        off = row.get("offset_s")
        if not isinstance(off, (int, float)) or off < 0:
            problems.append(f"requests[{i}].offset_s invalid: {off!r}")
        elif off < last_off:
            problems.append(f"requests[{i}].offset_s decreases ({off} < {last_off})")
        else:
            last_off = float(off)
        for field in ("prompt_tokens", "output_tokens"):
            v = row.get(field)
            if not isinstance(v, int) or v < 0:
                problems.append(f"requests[{i}].{field} invalid: {v!r}")
    return problems


__all__ = [
    "TRACE_VERSION",
    "export_trace",
    "export_fleet_trace",
    "stitch_timelines",
    "fleet_request_legs",
    "stitched_fleet_timelines",
    "validate_trace",
]
