"""OTLP metrics export — the reference's meter provider, standalone.

The reference initializes an OTLP-HTTP *meter* provider alongside its tracer
(``acp/internal/otel/otel.go:58-80``) with periodic export. This module does
the same for our in-tree Registry: a daemon thread snapshots the registry
every ``interval`` seconds and POSTs OTLP-JSON to
``$OTEL_EXPORTER_OTLP_ENDPOINT/v1/metrics`` — silent no-op when unset or
unreachable (otel.go's graceful-fallback posture). Counters map to
monotonic cumulative Sums, gauges to Gauges, and windowed histograms to
Summary points with p50/p90/p99 quantile values.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
import urllib.request
from typing import Optional

from .metrics import REGISTRY, Registry

log = logging.getLogger("acp_tpu.otel")

_NANOS = 1_000_000_000


def _attrs(labels: dict[str, str]) -> list[dict]:
    return [{"key": k, "value": {"stringValue": v}} for k, v in labels.items()]


def _to_otlp(snapshot: list[dict], start_nanos: int, now_nanos: int) -> dict:
    metrics = []
    for m in snapshot:
        if m["type"] == "counter":
            data = {
                "sum": {
                    "aggregationTemporality": 2,  # CUMULATIVE
                    "isMonotonic": True,
                    "dataPoints": [
                        {
                            "attributes": _attrs(s["labels"]),
                            "startTimeUnixNano": str(start_nanos),
                            "timeUnixNano": str(now_nanos),
                            "asDouble": s["value"],
                        }
                        for s in m["series"]
                    ],
                }
            }
        elif m["type"] == "gauge":
            data = {
                "gauge": {
                    "dataPoints": [
                        {
                            "attributes": _attrs(s["labels"]),
                            "timeUnixNano": str(now_nanos),
                            "asDouble": s["value"],
                        }
                        for s in m["series"]
                    ]
                }
            }
        else:  # histogram -> OTLP Summary (windowed quantiles)
            data = {
                "summary": {
                    "dataPoints": [
                        {
                            "attributes": _attrs(s["labels"]),
                            "startTimeUnixNano": str(start_nanos),
                            "timeUnixNano": str(now_nanos),
                            "count": str(s["count"]),
                            "sum": s["sum"],
                            "quantileValues": [
                                {"quantile": q, "value": v}
                                for q, v in s["quantiles"].items()
                            ],
                        }
                        for s in m["series"]
                    ]
                }
            }
        metrics.append({"name": m["name"], "description": m["help"], **data})
    return {
        "resourceMetrics": [
            {
                "resource": {
                    "attributes": [
                        {"key": "service.name", "value": {"stringValue": "acp-tpu"}}
                    ]
                },
                "scopeMetrics": [
                    {"scope": {"name": "acp-tpu"}, "metrics": metrics}
                ],
            }
        ]
    }


class MetricsExporter:
    """Periodic OTLP-JSON push of the registry. start() is a no-op without
    an endpoint, mirroring the tracer's silent fallback."""

    def __init__(
        self,
        registry: Registry = REGISTRY,
        endpoint: Optional[str] = None,
        interval: float = 30.0,
    ):
        if endpoint is None:
            endpoint = os.environ.get("OTEL_EXPORTER_OTLP_ENDPOINT", "")
        self.registry = registry
        self.endpoint = endpoint.rstrip("/")
        self.interval = interval
        self._start_nanos = int(time.time() * _NANOS)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        if not self.endpoint or self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._loop, name="otlp-metrics", daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    def export_once(self) -> bool:
        """One push; returns success. Used by the loop and by tests."""
        now = int(time.time() * _NANOS)
        doc = _to_otlp(self.registry.snapshot(), self._start_nanos, now)
        req = urllib.request.Request(
            f"{self.endpoint}/v1/metrics",
            data=json.dumps(doc).encode(),
            headers={"Content-Type": "application/json"},
            method="POST",
        )
        try:
            with urllib.request.urlopen(req, timeout=10) as resp:
                return 200 <= resp.status < 300
        except Exception as e:  # graceful no-op (otel.go:58-80 posture)
            log.debug("OTLP metrics export failed: %s", e)
            return False

    def _loop(self) -> None:
        while not self._stop.wait(self.interval):
            self.export_once()
