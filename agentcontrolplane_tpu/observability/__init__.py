from .flight import FlightRecorder, attribute_phases, phase_summaries
from .metrics import REGISTRY, Registry
from .otel_metrics import MetricsExporter
from .profiler import DispatchProfiler, WASTE_CAUSES
from .tracing import NOOP_TRACER, Span, Tracer, new_span_id, new_trace_id

__all__ = [
    "REGISTRY", "Registry", "MetricsExporter", "NOOP_TRACER", "Span", "Tracer",
    "new_span_id", "new_trace_id", "FlightRecorder", "attribute_phases",
    "phase_summaries", "DispatchProfiler", "WASTE_CAUSES",
]
