from .metrics import REGISTRY, Registry
from .tracing import NOOP_TRACER, Span, Tracer, new_span_id, new_trace_id

__all__ = ["REGISTRY", "Registry", "NOOP_TRACER", "Span", "Tracer", "new_span_id", "new_trace_id"]
