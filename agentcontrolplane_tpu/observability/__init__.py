from .flight import FlightRecorder, attribute_phases, phase_summaries
from .metrics import REGISTRY, Registry
from .otel_metrics import MetricsExporter
from .profiler import DispatchProfiler, WASTE_CAUSES
from .trace_export import (
    TRACE_VERSION,
    export_fleet_trace,
    export_trace,
    stitch_timelines,
    validate_trace,
)
from .tracing import NOOP_TRACER, Span, Tracer, new_span_id, new_trace_id

__all__ = [
    "REGISTRY", "Registry", "MetricsExporter", "NOOP_TRACER", "Span", "Tracer",
    "new_span_id", "new_trace_id", "FlightRecorder", "attribute_phases",
    "phase_summaries", "DispatchProfiler", "WASTE_CAUSES",
    "TRACE_VERSION", "export_trace", "export_fleet_trace",
    "stitch_timelines", "validate_trace",
]
