"""Persistent XLA compilation cache.

TPU compiles are the dominant cold-start cost (20-40s per jit entry
through the axon remote-compile service), and the serving engine has a
bounded-but-real matrix of programs (prefill buckets x batch sizes,
decode widths, constrained variants). The persistent cache makes every
compile a once-per-machine cost instead of once-per-process: the second
`acp-tpu run`, the driver's round-end `bench.py`, and every test process
reuse the same compiled artifacts.

Enabled by default; opt out with ``ACP_XLA_CACHE=0`` or point
``ACP_XLA_CACHE_DIR`` somewhere else (default ``~/.cache/acp_tpu_xla``).
"""

from __future__ import annotations

import logging
import os

log = logging.getLogger("acp_tpu.xla_cache")

_enabled = False


def enable_persistent_compilation_cache() -> bool:
    """Idempotent; safe to call before or after backend init (jax only
    consults the config at compile time). Returns True when active."""
    global _enabled
    if _enabled:
        return True
    if os.environ.get("ACP_XLA_CACHE", "1") in ("0", "false", "no"):
        return False
    try:
        import jax

        if jax.process_count() > 1:
            # Multi-host lockstep requires every rank to COMPILE the same
            # program the same way. A cache hit on one rank + fresh compile
            # on another can decompose collectives differently (observed as
            # gloo size-mismatch aborts on CPU meshes); per-process caches
            # also race on shared filesystems. Cold compiles are once per
            # process here — correctness wins.
            log.info("multi-host run: persistent compilation cache disabled")
            return False
    except Exception:
        pass  # backend not initialized yet; single-process paths continue
    cache_dir = os.environ.get("ACP_XLA_CACHE_DIR") or os.path.join(
        os.path.expanduser("~"), ".cache", "acp_tpu_xla"
    )
    try:
        os.makedirs(cache_dir, exist_ok=True)
        import jax

        jax.config.update("jax_compilation_cache_dir", cache_dir)
        # cache everything: the engine's programs are individually small but
        # numerous, and the default min-compile-time filter would skip the
        # narrow decode widths whose recompiles still cost a tunnel RTT
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
        _enabled = True
        log.info("persistent XLA compilation cache at %s", cache_dir)
    except Exception as e:  # never let cache plumbing break serving
        log.warning("persistent compilation cache unavailable: %s", e)
        return False
    return True
