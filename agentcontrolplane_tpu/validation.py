"""Input validation + k8s-style random names.

Rebuilt from ``acp/internal/validation/task_validation.go``.
"""

from __future__ import annotations

import secrets
from typing import Optional

from .api.resources import Message, Task
from .kernel.errors import Invalid, NotFound
from .kernel.store import Store

VALID_ROLES = {"system", "user", "assistant", "tool"}


def validate_task_message_input(
    user_message: Optional[str], context_window: Optional[list[Message]]
) -> None:
    """Exactly one of userMessage / contextWindow; window roles valid and must
    contain ≥1 user message (task_validation.go:16-39)."""
    has_msg = bool(user_message)
    has_window = bool(context_window)
    if has_msg and has_window:
        raise Invalid("only one of userMessage or contextWindow can be provided")
    if not has_msg and not has_window:
        raise Invalid("one of userMessage or contextWindow must be provided")
    if context_window:
        has_user = False
        for msg in context_window:
            if msg.role not in VALID_ROLES:
                raise Invalid(f"invalid role in contextWindow: {msg.role}")
            if msg.role == "user":
                has_user = True
        if not has_user:
            raise Invalid("contextWindow must contain at least one user message")


def get_user_message_preview(
    user_message: Optional[str], context_window: Optional[list[Message]]
) -> str:
    """50-char preview from userMessage or last user message
    (task_validation.go:42-59)."""
    preview = ""
    if user_message:
        preview = user_message
    elif context_window:
        for msg in reversed(context_window):
            if msg.role == "user":
                preview = msg.content
                break
    if len(preview) > 50:
        preview = preview[:47] + "..."
    return preview


_LETTERS = "abcdefghijklmnopqrstuvwxyz"
_ALNUM = _LETTERS + "0123456789"


def generate_k8s_random_string(n: int = 6) -> str:
    """Secure random k8s-compliant suffix: starts with a letter, lowercase
    alphanumeric, 1-8 chars (task_validation.go:61-87)."""
    if n < 1 or n > 8:
        n = 6
    return secrets.choice(_LETTERS) + "".join(
        secrets.choice(_ALNUM) for _ in range(n - 1)
    )


def validate_contact_channel_ref(store: Store, task: Task) -> None:
    """Referenced ContactChannel must exist and be ready
    (task_validation.go:90-110). A channel_token_from Task (v1beta3) carries
    its own per-task credentials, so readiness of the shared channel object is
    still required but API-key validation happened at channel level."""
    ref = task.spec.contact_channel_ref
    if ref is None:
        return
    try:
        channel = store.get("ContactChannel", ref.name, task.namespace)
    except NotFound:
        raise Invalid(f'referenced ContactChannel "{ref.name}" not found') from None
    if not channel.status.ready:
        raise Invalid(
            f'referenced ContactChannel "{ref.name}" is not ready '
            f"(status: {channel.status.status})"
        )
