"""MCP stdio transport: JSON-RPC 2.0 over a subprocess's stdin/stdout.

Equivalent of the reference's NewStdioMCPClient path
(``acp/internal/mcpmanager/mcpmanager.go:142``, via mark3labs/mcp-go):
newline-delimited JSON-RPC, ``initialize`` handshake, ``tools/list``,
``tools/call``.

Requests are MULTIPLEXED by JSON-RPC id: a background reader resolves
per-request futures, so concurrent ``call_tool``s to one server overlap
instead of serializing behind a single request-response lock — the
transport-level half of executing a turn's independent tool calls in
parallel (the ToolCall controller's workers provide the other half).
"""

from __future__ import annotations

import asyncio
import json
import os
from typing import Any, Optional

PROTOCOL_VERSION = "2024-11-05"


class MCPError(Exception):
    pass


def parse_quantity(q: str) -> int:
    """k8s memory quantity -> bytes ("512Mi", "1Gi", "100M", "1024")."""
    q = q.strip()
    units = {
        "Ki": 1024, "Mi": 1024**2, "Gi": 1024**3, "Ti": 1024**4,
        "K": 1000, "M": 1000**2, "G": 1000**3, "T": 1000**4, "k": 1000,
    }
    for suffix, mult in units.items():
        if q.endswith(suffix):
            return int(float(q[: -len(suffix)]) * mult)
    return int(float(q))


class StdioMCPClient:
    def __init__(
        self,
        command: str,
        args: list[str],
        env: dict[str, str] | None = None,
        memory_limit: int | None = None,  # bytes (spec.resources.limits.memory)
    ):
        self.command = command
        self.args = args
        self.env = env or {}
        self.memory_limit = memory_limit
        self._proc: Optional[asyncio.subprocess.Process] = None
        self._id = 0
        self._lock = asyncio.Lock()  # serializes stdin writes only
        self._pending: dict[int, asyncio.Future] = {}
        self._reader: Optional[asyncio.Task] = None
        self._dead: Optional[str] = None  # reader's terminal error, if any
        self.server_info: dict[str, Any] = {}

    def _argv(self) -> list[str]:
        """Command line, with the memory limit (the standalone equivalent of
        the reference's pod resource limits) applied via a ``/bin/sh ulimit``
        shim rather than ``preexec_fn``: preexec_fn forces subprocess down
        the fork() path, which is deadlock-prone (and warns loudly) in a
        process whose JAX runtime has live threads — the shim keeps the
        spawn on posix_spawn."""
        if not self.memory_limit or os.name != "posix":
            return [self.command, *self.args]
        kb = max(1, self.memory_limit // 1024)
        # ulimit soft-fails (';', stderr dropped): platforms that refuse
        # RLIMIT_AS still start the server limitless, matching the old
        # preexec_fn's graceful degradation
        return [
            "/bin/sh", "-c", f'ulimit -v {kb} 2>/dev/null; exec "$0" "$@"',
            self.command, *self.args,
        ]

    async def start(self, timeout: float = 15.0) -> None:
        env = dict(os.environ)
        env.update(self.env)
        self._proc = await asyncio.create_subprocess_exec(
            *self._argv(),
            stdin=asyncio.subprocess.PIPE,
            stdout=asyncio.subprocess.PIPE,
            stderr=asyncio.subprocess.DEVNULL,
            env=env,
        )
        self._reader = asyncio.ensure_future(self._read_loop())
        result = await self._request(
            "initialize",
            {
                "protocolVersion": PROTOCOL_VERSION,
                "capabilities": {},
                "clientInfo": {"name": "acp-tpu", "version": "0.1.0"},
            },
            timeout=timeout,
        )
        self.server_info = result.get("serverInfo", {})
        await self._notify("notifications/initialized", {})

    async def _send(self, msg: dict[str, Any]) -> None:
        assert self._proc and self._proc.stdin
        self._proc.stdin.write(json.dumps(msg).encode() + b"\n")
        await self._proc.stdin.drain()

    async def _read_loop(self) -> None:
        """Single stdout reader resolving pending requests by id. A dead
        pipe fails every in-flight and future request — concurrent callers
        must never hang on a response that can no longer arrive."""
        assert self._proc and self._proc.stdout
        error = f"MCP server {self.command} closed its stdout"
        try:
            while True:
                line = await self._proc.stdout.readline()
                if not line:
                    break
                try:
                    msg = json.loads(line)
                except json.JSONDecodeError:
                    continue  # stray non-protocol output
                fut = self._pending.pop(msg.get("id"), None)
                if fut is not None and not fut.done():
                    fut.set_result(msg)
        except asyncio.CancelledError:
            error = "MCP client closed"
        except Exception as e:
            error = f"MCP stdout reader failed: {e}"
        self._dead = error
        pending, self._pending = self._pending, {}
        for fut in pending.values():
            if not fut.done():
                fut.set_exception(MCPError(error))

    async def _request(self, method: str, params: dict[str, Any], timeout: float = 30.0) -> dict[str, Any]:
        if self._dead is not None:
            raise MCPError(self._dead)
        fut: asyncio.Future = asyncio.get_running_loop().create_future()
        async with self._lock:  # writes serialize; responses multiplex
            self._id += 1
            rid = self._id
            self._pending[rid] = fut
            # the reader sets _dead BEFORE swapping out the pending dict:
            # if it died between the fast-path check and this registration,
            # our future landed in the post-swap dict nobody will ever
            # sweep — re-checking AFTER registering closes the window
            # (dead already set => fail fast; dead set later => the sweep
            # sees our entry)
            if self._dead is not None:
                self._pending.pop(rid, None)
                raise MCPError(self._dead)
            try:
                await self._send({"jsonrpc": "2.0", "id": rid, "method": method, "params": params})
            except Exception:
                self._pending.pop(rid, None)
                raise
        try:
            msg = await asyncio.wait_for(fut, timeout)
        finally:
            self._pending.pop(rid, None)
        if "error" in msg:
            err = msg["error"]
            raise MCPError(f"{method}: {err.get('message')} ({err.get('code')})")
        return msg.get("result", {})

    async def _notify(self, method: str, params: dict[str, Any]) -> None:
        await self._send({"jsonrpc": "2.0", "method": method, "params": params})

    async def list_tools(self) -> list[dict[str, Any]]:
        result = await self._request("tools/list", {})
        return result.get("tools", [])

    async def call_tool(self, name: str, arguments: dict[str, Any], timeout: float = 60.0) -> dict[str, Any]:
        return await self._request("tools/call", {"name": name, "arguments": arguments}, timeout=timeout)

    @property
    def alive(self) -> bool:
        return self._proc is not None and self._proc.returncode is None

    async def close(self) -> None:
        if self._reader is not None:
            self._reader.cancel()
            try:
                await self._reader
            except (asyncio.CancelledError, Exception):
                pass
            self._reader = None
        if self._proc is None:
            return
        if self._proc.returncode is None:
            try:
                self._proc.terminate()
                await asyncio.wait_for(self._proc.wait(), 3.0)
            except (asyncio.TimeoutError, ProcessLookupError):
                try:
                    self._proc.kill()
                except ProcessLookupError:
                    pass
        self._proc = None
