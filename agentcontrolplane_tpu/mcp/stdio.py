"""MCP stdio transport: JSON-RPC 2.0 over a subprocess's stdin/stdout.

Equivalent of the reference's NewStdioMCPClient path
(``acp/internal/mcpmanager/mcpmanager.go:142``, via mark3labs/mcp-go):
newline-delimited JSON-RPC, ``initialize`` handshake, ``tools/list``,
``tools/call``.
"""

from __future__ import annotations

import asyncio
import json
import os
from typing import Any, Optional

PROTOCOL_VERSION = "2024-11-05"


class MCPError(Exception):
    pass


class StdioMCPClient:
    def __init__(self, command: str, args: list[str], env: dict[str, str] | None = None):
        self.command = command
        self.args = args
        self.env = env or {}
        self._proc: Optional[asyncio.subprocess.Process] = None
        self._id = 0
        self._lock = asyncio.Lock()
        self.server_info: dict[str, Any] = {}

    async def start(self, timeout: float = 15.0) -> None:
        env = dict(os.environ)
        env.update(self.env)
        self._proc = await asyncio.create_subprocess_exec(
            self.command,
            *self.args,
            stdin=asyncio.subprocess.PIPE,
            stdout=asyncio.subprocess.PIPE,
            stderr=asyncio.subprocess.DEVNULL,
            env=env,
        )
        result = await self._request(
            "initialize",
            {
                "protocolVersion": PROTOCOL_VERSION,
                "capabilities": {},
                "clientInfo": {"name": "acp-tpu", "version": "0.1.0"},
            },
            timeout=timeout,
        )
        self.server_info = result.get("serverInfo", {})
        await self._notify("notifications/initialized", {})

    async def _send(self, msg: dict[str, Any]) -> None:
        assert self._proc and self._proc.stdin
        self._proc.stdin.write(json.dumps(msg).encode() + b"\n")
        await self._proc.stdin.drain()

    async def _request(self, method: str, params: dict[str, Any], timeout: float = 30.0) -> dict[str, Any]:
        async with self._lock:
            self._id += 1
            rid = self._id
            await self._send({"jsonrpc": "2.0", "id": rid, "method": method, "params": params})
            assert self._proc and self._proc.stdout
            while True:
                line = await asyncio.wait_for(self._proc.stdout.readline(), timeout)
                if not line:
                    raise MCPError(f"MCP server {self.command} closed its stdout")
                try:
                    msg = json.loads(line)
                except json.JSONDecodeError:
                    continue  # stray non-protocol output
                if msg.get("id") != rid:
                    continue  # notification or unrelated message
                if "error" in msg:
                    err = msg["error"]
                    raise MCPError(f"{method}: {err.get('message')} ({err.get('code')})")
                return msg.get("result", {})

    async def _notify(self, method: str, params: dict[str, Any]) -> None:
        await self._send({"jsonrpc": "2.0", "method": method, "params": params})

    async def list_tools(self) -> list[dict[str, Any]]:
        result = await self._request("tools/list", {})
        return result.get("tools", [])

    async def call_tool(self, name: str, arguments: dict[str, Any], timeout: float = 60.0) -> dict[str, Any]:
        return await self._request("tools/call", {"name": name, "arguments": arguments}, timeout=timeout)

    @property
    def alive(self) -> bool:
        return self._proc is not None and self._proc.returncode is None

    async def close(self) -> None:
        if self._proc is None:
            return
        if self._proc.returncode is None:
            try:
                self._proc.terminate()
                await asyncio.wait_for(self._proc.wait(), 3.0)
            except (asyncio.TimeoutError, ProcessLookupError):
                try:
                    self._proc.kill()
                except ProcessLookupError:
                    pass
        self._proc = None
