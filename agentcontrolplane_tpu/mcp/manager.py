"""MCP server manager: connection pool + tool invocation.

Rebuilt from ``acp/internal/mcpmanager/mcpmanager.go`` (341 LoC): a pool
name -> (client, tools) guarded by a lock; stdio (subprocess) and http
transports; Secret-resolved env vars (``convertEnvVars``, 73-111); tool
invocation with text-content flattening (``CallTool``, 259-300).
"""

from __future__ import annotations

import asyncio
import json
from dataclasses import dataclass, field
from typing import Any, Optional, Protocol

from ..api.resources import MCPServer, MCPTool
from ..kernel.errors import Invalid
from ..kernel.store import Store
from ..llmclient.factory import resolve_secret_key
from .http import HTTPMCPClient
from .stdio import MCPError, StdioMCPClient


class MCPClient(Protocol):
    server_info: dict[str, Any]

    async def start(self, timeout: float = 15.0) -> None: ...
    async def list_tools(self) -> list[dict[str, Any]]: ...
    async def call_tool(self, name: str, arguments: dict[str, Any], timeout: float = 60.0) -> dict[str, Any]: ...
    async def close(self) -> None: ...
    @property
    def alive(self) -> bool: ...


@dataclass
class MCPConnection:
    name: str
    client: MCPClient
    tools: list[MCPTool] = field(default_factory=list)


def convert_env_vars(store: Store, namespace: str, server: MCPServer) -> dict[str, str]:
    """Resolve plain and Secret-sourced env vars (mcpmanager.go:73-111)."""
    env: dict[str, str] = {}
    for var in server.spec.env:
        if var.value is not None:
            env[var.name] = var.value
        elif var.value_from is not None:
            env[var.name] = resolve_secret_key(store, namespace, var.value_from)
        else:
            env[var.name] = ""
    return env


def flatten_tool_result(result: dict[str, Any]) -> str:
    """Flatten MCP content items to one string (mcpmanager.go:280-298):
    text items are concatenated; non-text items are JSON-encoded."""
    if result.get("isError"):
        parts = [
            c.get("text", "") for c in result.get("content", []) if c.get("type") == "text"
        ]
        raise MCPError("tool error: " + ("\n".join(parts) or json.dumps(result)))
    out: list[str] = []
    for item in result.get("content", []):
        if item.get("type") == "text":
            out.append(item.get("text", ""))
        else:
            out.append(json.dumps(item))
    return "\n".join(out)


class MCPManager:
    """One shared pool per operator process (cmd/main.go:241)."""

    def __init__(self, store: Optional[Store] = None):
        self._store = store
        self._connections: dict[str, MCPConnection] = {}
        self._lock = asyncio.Lock()

    def _make_client(self, server: MCPServer, env: dict[str, str]) -> MCPClient:
        if server.spec.transport == "stdio":
            if not server.spec.command:
                raise Invalid("stdio MCP server requires a command")
            mem_limit = None
            res = server.spec.resources
            if res is not None and res.limits.get("memory"):
                from .stdio import parse_quantity

                mem_limit = parse_quantity(res.limits["memory"])
            return StdioMCPClient(
                server.spec.command, list(server.spec.args), env, memory_limit=mem_limit
            )
        if server.spec.transport == "http":
            if not server.spec.url:
                raise Invalid("http MCP server requires a url")
            return HTTPMCPClient(server.spec.url)
        raise Invalid(f"unknown MCP transport {server.spec.transport!r}")

    async def connect_server(self, server: MCPServer) -> MCPConnection:
        """Connect (or reconnect), run the handshake, discover tools, cache
        in the pool (mcpmanager.go:113-218)."""
        env = (
            convert_env_vars(self._store, server.metadata.namespace, server)
            if self._store is not None
            else {v.name: v.value or "" for v in server.spec.env}
        )
        client = self._make_client(server, env)
        await client.start()
        raw_tools = await client.list_tools()
        tools = [
            MCPTool(
                name=t.get("name", ""),
                description=t.get("description", ""),
                input_schema=t.get("inputSchema") or {"type": "object", "properties": {}},
            )
            for t in raw_tools
        ]
        conn = MCPConnection(name=server.metadata.name, client=client, tools=tools)
        async with self._lock:
            old = self._connections.pop(server.metadata.name, None)
            self._connections[server.metadata.name] = conn
        if old is not None:
            await old.client.close()
        return conn

    def get_connection(self, name: str) -> Optional[MCPConnection]:
        return self._connections.get(name)

    def get_tools(self, name: str) -> list[MCPTool]:
        """Tools for one server (mcpmanager.go:248)."""
        conn = self._connections.get(name)
        return list(conn.tools) if conn else []

    def get_tools_map(self) -> dict[str, list[MCPTool]]:
        return {name: list(c.tools) for name, c in self._connections.items()}

    async def call_tool(self, server_name: str, tool_name: str, arguments: dict[str, Any]) -> str:
        """Invoke a tool; returns flattened text (mcpmanager.go:259-300)."""
        conn = self._connections.get(server_name)
        if conn is None:
            raise MCPError(f"MCP server {server_name!r} not connected")
        result = await conn.client.call_tool(tool_name, arguments)
        return flatten_tool_result(result)

    async def disconnect_server(self, name: str) -> None:
        async with self._lock:
            conn = self._connections.pop(name, None)
        if conn is not None:
            await conn.client.close()

    async def close(self) -> None:
        for name in list(self._connections):
            await self.disconnect_server(name)
