"""MCP HTTP transport (streamable-HTTP JSON-RPC; SSE responses supported).

Equivalent of the reference's SSE client path
(``acp/internal/mcpmanager/mcpmanager.go:148``).
"""

from __future__ import annotations

import json
from typing import Any, Optional

import httpx

from .stdio import MCPError, PROTOCOL_VERSION


def _parse_sse(text: str) -> dict[str, Any]:
    """Extract the last JSON data payload from an SSE body."""
    last = None
    for line in text.splitlines():
        if line.startswith("data:"):
            payload = line[5:].strip()
            if payload:
                try:
                    last = json.loads(payload)
                except json.JSONDecodeError:
                    continue
    if last is None:
        raise MCPError("no JSON payload in SSE response")
    return last


class HTTPMCPClient:
    def __init__(self, url: str, headers: dict[str, str] | None = None):
        self.url = url
        self._http = httpx.AsyncClient(timeout=30.0, headers=headers or {})
        self._id = 0
        self._session_id: Optional[str] = None
        self.server_info: dict[str, Any] = {}

    async def start(self, timeout: float = 15.0) -> None:
        result = await self._request(
            "initialize",
            {
                "protocolVersion": PROTOCOL_VERSION,
                "capabilities": {},
                "clientInfo": {"name": "acp-tpu", "version": "0.1.0"},
            },
        )
        self.server_info = result.get("serverInfo", {})
        await self._notify("notifications/initialized", {})

    async def _post(self, msg: dict[str, Any]) -> Optional[dict[str, Any]]:
        headers = {"Accept": "application/json, text/event-stream"}
        if self._session_id:
            headers["Mcp-Session-Id"] = self._session_id
        resp = await self._http.post(self.url, json=msg, headers=headers)
        if resp.status_code >= 400:
            raise MCPError(f"MCP http {resp.status_code}: {resp.text[:200]}")
        self._session_id = resp.headers.get("Mcp-Session-Id", self._session_id)
        if not resp.content:
            return None
        ctype = resp.headers.get("content-type", "")
        if "text/event-stream" in ctype:
            return _parse_sse(resp.text)
        return resp.json()

    async def _request(self, method: str, params: dict[str, Any]) -> dict[str, Any]:
        self._id += 1
        msg = await self._post(
            {"jsonrpc": "2.0", "id": self._id, "method": method, "params": params}
        )
        if msg is None:
            raise MCPError(f"{method}: empty response")
        if "error" in msg:
            err = msg["error"]
            raise MCPError(f"{method}: {err.get('message')} ({err.get('code')})")
        return msg.get("result", {})

    async def _notify(self, method: str, params: dict[str, Any]) -> None:
        try:
            await self._post({"jsonrpc": "2.0", "method": method, "params": params})
        except MCPError:
            pass  # some servers reject notifications; non-fatal

    async def list_tools(self) -> list[dict[str, Any]]:
        return (await self._request("tools/list", {})).get("tools", [])

    async def call_tool(self, name: str, arguments: dict[str, Any], timeout: float = 60.0) -> dict[str, Any]:
        return await self._request("tools/call", {"name": name, "arguments": arguments})

    @property
    def alive(self) -> bool:
        return True

    async def close(self) -> None:
        await self._http.aclose()
