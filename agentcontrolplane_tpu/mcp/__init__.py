from .adapters import (
    convert_mcp_tools,
    convert_sub_agents,
    parse_tool_arguments,
    split_tool_name,
)
from .http import HTTPMCPClient
from .manager import MCPConnection, MCPManager, convert_env_vars, flatten_tool_result
from .stdio import MCPError, StdioMCPClient

__all__ = [
    "convert_mcp_tools", "convert_sub_agents", "parse_tool_arguments",
    "split_tool_name", "HTTPMCPClient", "MCPConnection", "MCPManager",
    "convert_env_vars", "flatten_tool_result", "MCPError", "StdioMCPClient",
]
