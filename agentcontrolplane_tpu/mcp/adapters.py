"""MCP tool -> LLM tool-schema conversion.

Rebuilt from ``acp/internal/adapters/mcp_adapter.go:12-51``: tool names are
mangled ``server__tool`` so a single flat LLM tool namespace routes back to
the right server; missing schemas default to an empty object schema.
"""

from __future__ import annotations

import json
from typing import Any

from ..api.resources import Agent, MCPTool
from ..llmclient.base import MESSAGE_SCHEMA, Tool, ToolFunction

EMPTY_SCHEMA: dict[str, Any] = {"type": "object", "properties": {}}


def convert_mcp_tools(tools: list[MCPTool], server_name: str) -> list[Tool]:
    out = []
    for t in tools:
        out.append(
            Tool(
                function=ToolFunction(
                    name=f"{server_name}__{t.name}",
                    description=t.description,
                    parameters=t.input_schema or dict(EMPTY_SCHEMA),
                ),
                acp_tool_type="MCP",
            )
        )
    return out


def convert_sub_agents(agents: list[Agent]) -> list[Tool]:
    """Delegate tools ``delegate_to_agent__<name>`` with a message parameter
    (task_controller.go:94-117)."""
    return [
        Tool(
            function=ToolFunction(
                name=f"delegate_to_agent__{a.metadata.name}",
                description=a.spec.description,
                parameters=dict(MESSAGE_SCHEMA),
            ),
            acp_tool_type="DelegateToAgent",
        )
        for a in agents
    ]


def split_tool_name(name: str) -> tuple[str, str]:
    """``server__tool`` -> (server, tool). Raises on unmangled names."""
    if "__" not in name:
        raise ValueError(f"tool name {name!r} is not of the form server__tool")
    server, tool = name.split("__", 1)
    return server, tool


def parse_tool_arguments(arguments: str) -> dict[str, Any]:
    """JSON arguments string -> dict (mcp_adapter.go:54-60)."""
    try:
        parsed = json.loads(arguments or "{}")
    except json.JSONDecodeError as e:
        raise ValueError(f"failed to parse tool arguments: {e}") from e
    if not isinstance(parsed, dict):
        raise ValueError("tool arguments must be a JSON object")
    return parsed
