from .client import (
    ApprovalStatus,
    FunctionCallSpec,
    HTTPHumanLayerClient,
    HTTPHumanLayerClientFactory,
    HumanContactStatus,
    HumanLayerClient,
    HumanLayerClientFactory,
)
from .local import (
    LocalHumanBackend,
    LocalHumanLayerClient,
    LocalHumanLayerClientFactory,
    PendingApproval,
    PendingContact,
)

__all__ = [
    "ApprovalStatus", "FunctionCallSpec", "HTTPHumanLayerClient",
    "HTTPHumanLayerClientFactory", "HumanContactStatus", "HumanLayerClient",
    "HumanLayerClientFactory", "LocalHumanBackend", "LocalHumanLayerClient",
    "LocalHumanLayerClientFactory", "PendingApproval", "PendingContact",
]
