"""In-tree human interaction backend.

The reference delegates approvals/contacts to the HumanLayer SaaS; standalone
TPU-native operation needs an in-tree equivalent. Pending interactions are
held here and surfaced through the REST API (``/v1/approvals``,
``/v1/contacts``) where a human (or test) approves / rejects / responds.
Doubles as the scriptable mock (the reference's hand-written
``mock_hlclient.go`` knobs: ShouldFail / ShouldReturnApproval / Rejection).
"""

from __future__ import annotations

import time
import uuid
from dataclasses import dataclass, field
from typing import Any, Optional

from .client import ApprovalStatus, FunctionCallSpec, HumanContactStatus


@dataclass
class PendingApproval:
    call_id: str
    run_id: str
    fn: str
    kwargs: dict[str, Any]
    channel: Optional[dict[str, Any]]
    created: float
    approved: Optional[bool] = None
    comment: str = ""


@dataclass
class PendingContact:
    call_id: str
    run_id: str
    message: str
    channel: Optional[dict[str, Any]]
    created: float
    response: Optional[str] = None


@dataclass
class LocalHumanBackend:
    """Shared state: one instance per operator process; every channel's
    client resolves to it."""

    approvals: dict[str, PendingApproval] = field(default_factory=dict)
    contacts: dict[str, PendingContact] = field(default_factory=dict)
    # mock knobs (mock_hlclient.go:13-24)
    should_fail: bool = False
    auto_approve: Optional[bool] = None  # True/False = instant verdict
    auto_respond: Optional[str] = None

    # -- human-side API (REST server / tests call these) -----------------

    def approve(self, call_id: str, comment: str = "") -> None:
        self.approvals[call_id].approved = True
        self.approvals[call_id].comment = comment

    def reject(self, call_id: str, comment: str = "") -> None:
        self.approvals[call_id].approved = False
        self.approvals[call_id].comment = comment

    def respond(self, call_id: str, response: str) -> None:
        self.contacts[call_id].response = response

    def pending_approvals(self) -> list[PendingApproval]:
        return [a for a in self.approvals.values() if a.approved is None]

    def pending_contacts(self) -> list[PendingContact]:
        return [c for c in self.contacts.values() if c.response is None]


class LocalHumanLayerClient:
    """Client view over a LocalHumanBackend (implements HumanLayerClient)."""

    def __init__(self, backend: LocalHumanBackend):
        self._b = backend

    async def request_approval(self, run_id: str, call_id: str, spec: FunctionCallSpec) -> str:
        if self._b.should_fail:
            raise RuntimeError("human backend unavailable (scripted failure)")
        call_id = call_id or uuid.uuid4().hex[:12]
        self._b.approvals[call_id] = PendingApproval(
            call_id=call_id,
            run_id=run_id,
            fn=spec.fn,
            kwargs=spec.kwargs,
            channel=spec.channel,
            created=time.time(),
            approved=self._b.auto_approve,
            comment="" if self._b.auto_approve is None else "auto",
        )
        return call_id

    async def get_function_call_status(self, call_id: str) -> ApprovalStatus:
        if self._b.should_fail:
            raise RuntimeError("human backend unavailable (scripted failure)")
        a = self._b.approvals[call_id]
        return ApprovalStatus(approved=a.approved, comment=a.comment)

    async def request_human_contact(
        self, run_id: str, call_id: str, message: str, channel: Optional[dict[str, Any]] = None
    ) -> str:
        if self._b.should_fail:
            raise RuntimeError("human backend unavailable (scripted failure)")
        call_id = call_id or uuid.uuid4().hex[:12]
        self._b.contacts[call_id] = PendingContact(
            call_id=call_id,
            run_id=run_id,
            message=message,
            channel=channel,
            created=time.time(),
            response=self._b.auto_respond,
        )
        return call_id

    async def get_human_contact_status(self, call_id: str) -> HumanContactStatus:
        if self._b.should_fail:
            raise RuntimeError("human backend unavailable (scripted failure)")
        return HumanContactStatus(response=self._b.contacts[call_id].response)

    async def verify_project(self) -> dict[str, Any]:
        if self._b.should_fail:
            raise RuntimeError("human backend unavailable (scripted failure)")
        return {"project": "local", "org": "local"}


class LocalHumanLayerClientFactory:
    def __init__(self, backend: Optional[LocalHumanBackend] = None):
        self.backend = backend or LocalHumanBackend()

    def create_client(self, api_key: str) -> LocalHumanLayerClient:
        return LocalHumanLayerClient(self.backend)
