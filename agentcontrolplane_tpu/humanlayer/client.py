"""Human approval / contact clients.

Rebuilt from the reference's HumanLayer wrapper
(``acp/internal/humanlayer/hlclient.go:55-69``: request approval, request
human contact, poll statuses) with two implementations:

- ``HTTPHumanLayerClient`` — speaks the HumanLayer-compatible HTTP API
  (``HUMANLAYER_API_BASE``), like the generated client in
  ``acp/internal/humanlayerapi/``.
- ``LocalHumanBackend`` (local.py) — in-tree approval/contact service
  surfaced through our REST API, so human-in-loop works with zero external
  SaaS (TPU-native standalone goal).
"""

from __future__ import annotations

import os
from dataclasses import dataclass
from typing import Any, Optional, Protocol

import httpx

DEFAULT_API_BASE = "https://api.humanlayer.dev/humanlayer/v1"
API_TIMEOUT = 10.0  # reference task_controller.go:24


@dataclass
class FunctionCallSpec:
    """What the human is asked to approve (fn name + kwargs + channel)."""

    fn: str
    kwargs: dict[str, Any]
    channel: Optional[dict[str, Any]] = None


@dataclass
class ApprovalStatus:
    approved: Optional[bool] = None  # None = still pending
    comment: str = ""


@dataclass
class HumanContactStatus:
    response: Optional[str] = None  # None = still pending


class HumanLayerClient(Protocol):
    """The seam (hlclient.go:55-69); toolcall controller depends only on this."""

    async def request_approval(self, run_id: str, call_id: str, spec: FunctionCallSpec) -> str: ...

    async def get_function_call_status(self, call_id: str) -> ApprovalStatus: ...

    async def request_human_contact(
        self, run_id: str, call_id: str, message: str, channel: Optional[dict[str, Any]] = None
    ) -> str: ...

    async def get_human_contact_status(self, call_id: str) -> HumanContactStatus: ...


class HumanLayerClientFactory(Protocol):
    def create_client(self, api_key: str) -> HumanLayerClient: ...


class HTTPHumanLayerClient:
    """HumanLayer-compatible HTTP API client (humanlayerapi/api_default.go
    surface: function_calls + contact_requests, polled)."""

    def __init__(self, api_key: str, base_url: Optional[str] = None):
        self._http = httpx.AsyncClient(
            base_url=base_url or os.environ.get("HUMANLAYER_API_BASE", DEFAULT_API_BASE),
            headers={"Authorization": f"Bearer {api_key}"},
            timeout=API_TIMEOUT,
        )

    async def request_approval(self, run_id: str, call_id: str, spec: FunctionCallSpec) -> str:
        body = {
            "run_id": run_id,
            "call_id": call_id,
            "spec": {"fn": spec.fn, "kwargs": spec.kwargs},
        }
        if spec.channel:
            body["spec"]["channel"] = spec.channel
        resp = await self._http.post("/function_calls", json=body)
        resp.raise_for_status()
        return resp.json().get("call_id", call_id)

    async def get_function_call_status(self, call_id: str) -> ApprovalStatus:
        resp = await self._http.get(f"/function_calls/{call_id}")
        resp.raise_for_status()
        status = resp.json().get("status") or {}
        return ApprovalStatus(
            approved=status.get("approved"), comment=status.get("comment") or ""
        )

    async def request_human_contact(
        self, run_id: str, call_id: str, message: str, channel: Optional[dict[str, Any]] = None
    ) -> str:
        body = {"run_id": run_id, "call_id": call_id, "spec": {"msg": message}}
        if channel:
            body["spec"]["channel"] = channel
        resp = await self._http.post("/contact_requests", json=body)
        resp.raise_for_status()
        return resp.json().get("call_id", call_id)

    async def get_human_contact_status(self, call_id: str) -> HumanContactStatus:
        resp = await self._http.get(f"/contact_requests/{call_id}")
        resp.raise_for_status()
        status = resp.json().get("status") or {}
        return HumanContactStatus(response=status.get("response"))

    async def verify_project(self) -> dict[str, Any]:
        """Credential check used by the ContactChannel controller
        (contactchannel/state_machine.go:214 equivalent)."""
        resp = await self._http.get("/project")
        resp.raise_for_status()
        return resp.json()

    async def close(self) -> None:
        await self._http.aclose()


class HTTPHumanLayerClientFactory:
    def __init__(self, base_url: Optional[str] = None):
        self.base_url = base_url

    def create_client(self, api_key: str) -> HTTPHumanLayerClient:
        return HTTPHumanLayerClient(api_key, self.base_url)
