"""Builder fixtures per kind, with setup / setup_with_status / teardown —
plus the deterministic fault-injection seam (:data:`FAULTS`).

Mirrors the reference's ``test/utils/*.go`` (SURVEY.md §4): the universal
trick is ``setup_with_status`` — write status directly through the status
subresource so a test can fabricate "LLM is Ready" without live API keys.

Fault injection
---------------

``FAULTS`` lives in :mod:`agentcontrolplane_tpu.faults` (a dependency-free
module so the engine can import it without this fixture surface) and is
re-exported here for test convenience — see that module's docstring for
the site catalogue and determinism contract.
"""

from __future__ import annotations

from agentcontrolplane_tpu.api import ObjectMeta
from agentcontrolplane_tpu.api.resources import (
    Agent,
    AgentSpec,
    BaseConfig,
    ContactChannel,
    ContactChannelSpec,
    EmailChannelConfig,
    LLM,
    LLMSpec,
    LocalObjectRef,
    MCPServer,
    MCPServerSpec,
    MCPTool,
    Message,
    Secret,
    SecretKeyRef,
    SecretSpec,
    Task,
    TaskSpec,
    ToolCall,
    ToolCallSpec,
)
from agentcontrolplane_tpu.kernel import NotFound, Store


def setup_with_status(store: Store, obj, status_mutator=None):
    created = store.create(obj)
    if status_mutator is not None:
        status_mutator(created)
        created = store.update_status(created)
    return created


def teardown(store: Store, obj) -> None:
    try:
        store.delete(obj.kind, obj.metadata.name, obj.metadata.namespace)
    except NotFound:
        pass


def make_secret(store: Store, name="test-secret", data=None) -> Secret:
    return store.create(
        Secret(
            metadata=ObjectMeta(name=name),
            spec=SecretSpec(data=data or {"api-key": "sk-test-123"}),
        )
    )


def make_llm(store: Store, name="test-llm", provider="mock", ready=True, **kwargs) -> LLM:
    spec = LLMSpec(
        provider=provider,
        api_key_from=SecretKeyRef(name="test-secret", key="api-key")
        if provider in ("openai", "anthropic", "mistral", "google")
        else None,
        parameters=BaseConfig(model=kwargs.pop("model", "test-model")),
        **kwargs,
    )
    def mark_ready(o):
        o.status.ready = True
        o.status.status = "Ready"
    return setup_with_status(
        store, LLM(metadata=ObjectMeta(name=name), spec=spec), mark_ready if ready else None
    )


def make_agent(
    store: Store,
    name="test-agent",
    llm="test-llm",
    system="you are a helpful assistant",
    ready=True,
    mcp_servers=(),
    channels=(),
    sub_agents=(),
    resolved_tools=None,
    description="",
) -> Agent:
    spec = AgentSpec(
        llm_ref=LocalObjectRef(name=llm),
        system=system,
        description=description,
        mcp_servers=[LocalObjectRef(name=s) for s in mcp_servers],
        human_contact_channels=[LocalObjectRef(name=c) for c in channels],
        sub_agents=[LocalObjectRef(name=a) for a in sub_agents],
    )

    def mark_ready(o):
        o.status.ready = True
        o.status.status = "Ready"
        from agentcontrolplane_tpu.api.resources import ResolvedMCPServer, ResolvedSubAgent

        o.status.valid_mcp_servers = [
            ResolvedMCPServer(name=s, tools=(resolved_tools or {}).get(s, []))
            for s in mcp_servers
        ]
        o.status.valid_human_contact_channels = list(channels)
        o.status.valid_sub_agents = [ResolvedSubAgent(name=a) for a in sub_agents]

    return setup_with_status(
        store, Agent(metadata=ObjectMeta(name=name), spec=spec), mark_ready if ready else None
    )


def make_task(
    store: Store,
    name="test-task",
    agent="test-agent",
    user_message="what is the capital of france?",
    context_window=None,
    labels=None,
    **kwargs,
) -> Task:
    return store.create(
        Task(
            metadata=ObjectMeta(name=name, labels=labels or {}),
            spec=TaskSpec(
                agent_ref=LocalObjectRef(name=agent),
                user_message=user_message,
                context_window=context_window,
                **kwargs,
            ),
        )
    )


def make_toolcall(
    store: Store,
    name="test-task-abc1234-tc-01",
    task="test-task",
    tool="fetch__fetch",
    tool_type="MCP",
    arguments='{"url": "https://example.com"}',
    labels=None,
    owner=None,
) -> ToolCall:
    meta = ObjectMeta(name=name, labels=labels or {})
    if owner is not None:
        meta.owner_references = [owner.owner_ref()]
    return store.create(
        ToolCall(
            metadata=meta,
            spec=ToolCallSpec(
                tool_call_id="call_1",
                task_ref=LocalObjectRef(name=task),
                tool_ref=LocalObjectRef(name=tool),
                tool_type=tool_type,
                arguments=arguments,
            ),
        )
    )


def make_mcpserver(store: Store, name="fetch", connected=True, tools=("fetch",), approval_channel=None) -> MCPServer:
    def mark_connected(o):
        o.status.connected = True
        o.status.status = "Ready"
        o.status.tools = [MCPTool(name=t, description=f"{t} tool") for t in tools]

    return setup_with_status(
        store,
        MCPServer(
            metadata=ObjectMeta(name=name),
            spec=MCPServerSpec(
                transport="stdio",
                command="echo",
                approval_contact_channel=approval_channel,
            ),
        ),
        mark_connected if connected else None,
    )


def make_contactchannel(store: Store, name="approval-channel", ready=True) -> ContactChannel:
    def mark_ready(o):
        o.status.ready = True
        o.status.status = "Ready"

    return setup_with_status(
        store,
        ContactChannel(
            metadata=ObjectMeta(name=name),
            spec=ContactChannelSpec(
                type="email",
                api_key_from=SecretKeyRef(name="test-secret", key="api-key"),
                email=EmailChannelConfig(address="human@example.com"),
            ),
        ),
        mark_ready if ready else None,
    )


# ---------------------------------------------------------------------------
# Fault injection — re-exported from the dependency-free faults module
# ---------------------------------------------------------------------------

from agentcontrolplane_tpu.faults import FAULTS, FaultInjector  # noqa: E402,F401
