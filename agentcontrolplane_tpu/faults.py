"""Deterministic fault injection (:data:`FAULTS`).

``FAULTS`` is a process-wide :class:`FaultInjector`. Production code keeps
the hooks near-free: every site is guarded by ``FAULTS.enabled`` (a plain
bool, False unless ``$ACP_FAULTS`` is truthy or a test calls
``FAULTS.enable()``). Faults are **deterministic**: they arm by site name
with explicit trigger conditions (fire-count budgets, step thresholds),
never randomness — a stress test that injects page pressure or a forced
preemption at decode step N reproduces byte-identically.

Engine sites (see ``engine/engine.py``):

- ``engine.crash``         — raise inside the engine loop (crash recovery).
- ``engine.queue_full``    — ``submit()`` sheds as if the admission queue
  were at its cap (503 end to end).
- ``engine.force_preempt`` — preempt the policy victim at the first decode
  block where ``decode_steps >= after_steps``.
- ``engine.preempt_mid_prefill`` — force preemption to land on a
  PARTIALLY-PREFILLED slot (chunked prefill): at the first scheduler round
  where ``prefill_chunks >= after_steps``, the mid-prefill slot with the
  most chunk progress is preempted — its partial prompt KV is released and
  the request re-enters the chunk loop on re-admission (byte-identical;
  nothing was sampled). Arm with ``after_steps=N`` to let N chunks land
  first. Fires only while some slot is mid-prefill.
- ``engine.slow_cycle`` — stretch the next ``times=N`` scheduler cycles
  by ``delay_s`` each (a ``time.sleep`` in the engine loop): a throttle
  drill so wall-clock races — tight deadlines, mid-flight cancels — land
  while requests are genuinely queued or decoding, which a tiny model on
  fast hardware otherwise outruns. Timing-only: sampled tokens are
  untouched. Consumed on BUSY cycles only (idle admission-park wakeups
  never drain the budget), so ``times=N`` means N cycles that were doing
  work. Arm with ``replica="<fleet_replica_id>"`` to throttle ONE pool
  member — the gray-replica drill the stall watchdog, health state
  machine and hedged re-dispatch are tested against (fleet/health.py,
  docs/fleet.md). The ``cancel_churn`` scenario trace and the chaos
  conductor arm this site (``scenarios/library.py``, docs/scenarios.md).
- ``engine.page_pressure`` — hold ``pages`` KV pages out of the allocator
  (released when disarmed/reset), shrinking the pool mid-serve.
- ``engine.invariant_break`` — corrupt a mirror counter (``_parked_count``)
  right before the armed invariant checker runs, proving the
  ``ACP_INVARIANTS`` audit trips end to end (engine crashes with
  ``InvariantViolation``; callers' futures fail; ``ensure_running``
  recovers). Gated on ``Engine.check_invariants`` so arming it against a
  disarmed engine is a no-op instead of silent state corruption. With
  ``$ACP_FLIGHT_DUMP_DIR`` set this site also proves the flight recorder's
  crash-dump path end to end: the crash handler snapshots the last-N
  flight events (including the ``invariant_violation`` event itself) +
  ``Engine.stats()`` + the paged allocator audit to a JSON dump before the
  loud crash (observability/flight.py, docs/debugging-guide.md).
- ``engine.host_swap_slow`` — stretch the next ``times=N`` host-tier KV
  swap operations (swap-out at preemption/park-expiry, or the first
  restore chunk of a swap-in) by ``seconds=S`` each: a saturated host
  memory bus / NUMA-remote pool. The stall is visible as the flight
  recorder's ``host_stall`` phase; outputs stay byte-identical (swapping
  only moves WHERE resume KV comes from, never what is sampled).
- ``engine.host_swap_error`` — fail the next ``times=N`` host-tier swap
  operations: a swap-out aborts before its entry lands (resume falls back
  to recomputing the prefill), a swap-in abandons its restore and the
  slot recomputes from its restored position. Deterministic and graceful
  — the host tier is an optimization, so every failure degrades to
  today's discard-and-recompute path, byte-identically.
- ``engine.prefetch_error`` — abort the next ``times=N`` async host-KV
  prefetch commits (the staged host->device restore copies launched a
  cycle ahead by the paged engine's swap-in prefetcher): the staged
  arrays are discarded and the chunk degrades to the blocking
  ``_swap_in_rows`` copy, byte-identically — prefetch only overlaps WHEN
  the copy happens, never what lands in the pages. Each abort records a
  ``prefetch_abort`` flight event; the lost overlap shows up as
  ``host_stall`` seconds that prefetch would have hidden.
- ``engine.spec_mismatch`` — force the WORST CASE for speculative decoding:
  for the next ``times=N`` verify dispatches every draft token is treated
  as mismatched (full rejection), so each dispatch commits exactly one
  (still byte-identical) corrected token and the whole rejected tail's KV
  is rolled back. Exercises the rollback path and the adaptive draft-length
  decay without perturbing outputs — the accept op always emits the
  verified model token, never the draft.

Fleet sites (see ``fleet/router.py`` and ``engine/engine.py``, pool
failover + disaggregation stress):

- ``fleet.replica_crash`` — crash the engine loop of ONE named replica in
  a pool: arm with ``replica="<fleet_replica_id>"`` (and optionally
  ``after_steps=N`` decode steps so it lands mid-decode). The ``match``
  filter keeps sibling engines in the same process alive — only the named
  replica raises; the router fails its in-flight + queued work over to
  survivors through the normal resubmission path. Armed without
  ``replica=``, the first fleet-registered engine loop to check fires.
- ``fleet.handoff_error`` — drop the next ``times=N`` prefill→decode
  handoff entries between export and inject, as if the wire transfer
  failed: the decode replica never sees the entry and runs a full local
  prefill instead. Deterministic and graceful — disaggregation is an
  optimization, so output stays byte-identical, only TTFT pays.
- ``fleet.route_stale`` — treat the next ``times=N`` affinity-map hits as
  stale (the mapped replica evicted the persona / restarted): the router
  counts a miss, falls back to least-loaded, and re-homes the key —
  the graceful path a real eviction or replica restart exercises.

Tool-execution sites (see ``controllers/toolcall.py``, overlapped tool
execution stress):

- ``tool.slow``  — stretch the next ``times=N`` MCP executions by
  ``seconds=S`` each (a slow tool outliving its turn's parked slot).
- ``tool.error`` — fail the next ``times=N`` MCP executions before the
  call reaches the server; the failure joins the conversation as an error
  tool result (the state machine's normal posture), never a crash.

This module is deliberately dependency-free (stdlib only) so the engine
can import it without pulling in the control-plane kernel or the test
fixtures in :mod:`agentcontrolplane_tpu.testing`, which re-exports
``FAULTS`` for test convenience.
"""

from __future__ import annotations

import os
import threading
from typing import Optional


class FaultInjector:
    """Deterministic, site-keyed fault injection.

    Thread-safe: arm/disarm happen on test threads while ``pop`` /
    ``apply_page_pressure`` run on the engine thread. A site armed with
    ``times=N`` fires at most N times; ``after_steps`` gates firing until
    the caller-supplied ``steps`` context reaches the threshold.
    """

    def __init__(self) -> None:
        self.enabled = bool(os.environ.get("ACP_FAULTS", ""))
        self._lock = threading.Lock()
        self._armed: dict[str, dict] = {}
        # site "engine.page_pressure": pages held per allocator (by id);
        # the allocator reference is kept so reset() can release them
        self._held: dict[int, tuple[object, list[int]]] = {}

    def enable(self) -> None:
        self.enabled = True

    def arm(self, site: str, *, times: int = 1, after_steps: int = 0, **spec) -> None:
        """Arm ``site`` to fire ``times`` times once ``steps >= after_steps``.
        Extra keywords ride along in the spec the call site receives."""
        self.enable()
        with self._lock:
            self._armed[site] = {"times": times, "after_steps": after_steps, **spec}

    def disarm(self, site: str) -> None:
        with self._lock:
            self._armed.pop(site, None)

    def armed(self, site: str) -> bool:
        with self._lock:
            return site in self._armed

    def pop(self, site: str, steps: int = 0, match: Optional[dict] = None):
        """Consume one firing of ``site`` if armed and due; returns the spec
        dict (or None). Call sites guard with ``FAULTS.enabled`` first so
        the disabled path costs one attribute read.

        ``match`` scopes a fault to a specific call site without consuming
        the budget elsewhere: for every key present in BOTH ``match`` and
        the armed spec, the values must be equal or the pop is a no-op
        (e.g. ``fleet.replica_crash`` armed with ``replica="r1"`` fires
        only in the engine whose ``fleet_replica_id`` is ``"r1"``; a spec
        armed without the key fires at any matching site)."""
        with self._lock:
            spec = self._armed.get(site)
            if spec is None or steps < spec["after_steps"]:
                return None
            if match:
                for k, v in match.items():
                    if k in spec and spec[k] != v:
                        return None
            spec["times"] -= 1
            if spec["times"] <= 0:
                del self._armed[site]
            return dict(spec)

    def held_pages(self, allocator) -> list[int]:
        """Pages ``engine.page_pressure`` is holding out of ``allocator``
        — the invariant checker's ownership audit counts them as owned (a
        held page is referenced on purpose, not leaked)."""
        with self._lock:
            ent = self._held.get(id(allocator))
            return list(ent[1]) if ent else []

    def apply_page_pressure(self, allocator) -> None:
        """Converge the pages held from ``allocator`` toward the armed
        ``engine.page_pressure`` spec (``pages=N``; 0/disarmed releases).
        Engine-thread only — the allocator is engine-thread-owned."""
        with self._lock:
            spec = self._armed.get("engine.page_pressure")
            want = int(spec["pages"]) if spec else 0
            _, held = self._held.setdefault(id(allocator), (allocator, []))
            if len(held) < want:
                take = min(want - len(held), allocator.free_count)
                if take:
                    held.extend(allocator.alloc(take))
            elif len(held) > want:
                allocator.free(held[want:])
                del held[want:]

    def reset(self) -> None:
        """Disarm everything and release held pages. Tests call this in
        teardown; safe while engines still run (page release is the same
        allocator mutation the engine thread performs, so only call after
        the engine is stopped or idle)."""
        with self._lock:
            self._armed.clear()
            held, self._held = self._held, {}
        for allocator, pages in held.values():
            if pages:
                allocator.free(pages)
        self.enabled = bool(os.environ.get("ACP_FAULTS", ""))


FAULTS = FaultInjector()
