from .llama import (
    PRESETS,
    LlamaConfig,
    decode_step,
    forward,
    init_kv_cache,
    init_params,
    prefill,
)

__all__ = [
    "PRESETS", "LlamaConfig", "decode_step", "forward", "init_kv_cache",
    "init_params", "prefill",
]
