"""Llama model family, TPU-first pure-JAX implementation.

No reference analogue (humanlayer/agentcontrolplane runs no models —
SURVEY.md §0); this is the compute core of the in-tree ``provider: tpu``
backend (north star: Llama-3-8B serving on v5e-8).

Design choices for TPU/XLA:

- Params are a plain pytree with **stacked layer weights** (leading dim =
  n_layers) so the transformer body is one ``lax.scan`` — O(1) HLO size and
  compile time in depth, and XLA pipelines the layer loop.
- bf16 params/activations (MXU-native), float32 for norms/softmax/rope.
- GQA (n_kv_heads <= n_heads), SwiGLU MLP, RMSNorm, RoPE — weight layout
  matches HF ``LlamaForCausalLM`` so checkpoints load without surgery.
- Three entry points: ``forward`` (full sequence — training/prefill/tests),
  ``prefill`` (writes a slot KV cache), ``decode_step`` (one token for all
  slots of the continuous batch).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from functools import partial
from typing import Any, Optional

import jax
import jax.numpy as jnp

from ..ops.attention import (
    blocked_causal_attention,
    causal_attention,
    continue_attention,
    decode_attention_cache_plus_new,
)
from ..ops.norms import rms_norm
from ..ops.quant import kv_dequantize, kv_quantize
from ..ops.rope import apply_rope


@dataclass(frozen=True)
class LlamaConfig:
    """Covers the Llama-architecture family: Llama-3/3.x, Mistral (same
    block; sliding window unused at our context lengths), Qwen2/2.5
    (``qkv_bias=True``), and Gemma-1 (``hidden_act="gelu_tanh"``,
    ``norm_plus_one``, ``embed_scale``, explicit ``head_dim`` — MQA)."""

    vocab_size: int = 128256
    dim: int = 4096
    n_layers: int = 32
    n_heads: int = 32
    n_kv_heads: int = 8
    ffn_dim: int = 14336
    norm_eps: float = 1e-5
    rope_theta: float = 500000.0
    # Llama-3.1-style RoPE frequency rescale (HF rope_scaling.rope_type
    # "llama3"): factor > 1 enables (8.0 for 3.1, 32.0 for 3.2); the other
    # three follow the checkpoint config. Real 3.1/3.2 checkpoints are
    # TRAINED with these — serving them unscaled is a different function.
    rope_scaling_factor: float = 1.0
    rope_low_freq_factor: float = 1.0
    rope_high_freq_factor: float = 4.0
    rope_original_max_seq: int = 8192
    max_seq_len: int = 8192
    tie_embeddings: bool = False
    qkv_bias: bool = False  # Qwen2-style attention input bias
    hidden_act: str = "silu"  # "silu" (llama/mistral/qwen) | "gelu_tanh" (gemma)
    norm_plus_one: bool = False  # gemma RMSNorm multiplies by (1 + weight)
    embed_scale: bool = False  # gemma scales embeddings by sqrt(dim)
    head_dim_override: Optional[int] = None  # gemma: head_dim != dim/n_heads
    # Gemma-2 additions (all default-off => prior families unchanged):
    attn_logit_softcap: float = 0.0  # tanh-cap attention logits (g2: 50.0)
    final_logit_softcap: float = 0.0  # tanh-cap lm_head logits (g2: 30.0)
    post_norms: bool = False  # extra RMSNorms on sublayer OUTPUTS pre-residual
    query_pre_attn_scalar: float = 0.0  # q scale denominator; 0 = head_dim
    # Gemma-2 alternates local (sliding-window) and global layers. Within
    # one window sliding == full causal, so serving is EXACT for contexts
    # <= window (4096) and the engine refuses longer (models this size
    # rarely need it; a windowed KV path is future work).
    sliding_window: int = 0
    # Mixture-of-Experts (Mixtral architecture): n_experts > 0 replaces the
    # dense FFN with top-k routed SwiGLU experts (ops/moe.py). The expert
    # axis shards over the mesh's 'ep' axis (expert parallelism).
    n_experts: int = 0
    experts_per_token: int = 2
    # GShard capacity factor: each expert accepts at most
    # ceil(factor * tokens * k / E) tokens per dispatch; overflow falls back
    # to the residual stream. 2.0 keeps drops negligible at serving batch
    # sizes; tests use no-drop capacities.
    expert_capacity_factor: float = 2.0
    # dispatch/combine group size: tokens are routed in fixed-size groups so
    # the one-hot dispatch tensors stay O(group) per token instead of O(N)
    moe_group_size: int = 512
    dtype: Any = jnp.bfloat16

    @property
    def head_dim(self) -> int:
        if self.head_dim_override is not None:
            return self.head_dim_override
        return self.dim // self.n_heads


# Presets: llama3-8b matches meta-llama/Meta-Llama-3-8B(-Instruct);
# llama3.2-1b matches meta-llama/Llama-3.2-1B(-Instruct).
PRESETS: dict[str, LlamaConfig] = {
    "llama3-8b": LlamaConfig(),
    # 3.1 = the 3-8B architecture + llama3 rope scaling to 128k context
    "llama3.1-8b": LlamaConfig(
        rope_scaling_factor=8.0,
        rope_low_freq_factor=1.0,
        rope_high_freq_factor=4.0,
        rope_original_max_seq=8192,
        max_seq_len=131072,
    ),
    "llama3.2-1b": LlamaConfig(
        vocab_size=128256,
        dim=2048,
        n_layers=16,
        n_heads=32,
        n_kv_heads=8,
        ffn_dim=8192,
        rope_theta=500000.0,
        tie_embeddings=True,
        rope_scaling_factor=32.0,
        rope_original_max_seq=8192,
        max_seq_len=131072,
    ),
    "llama3.2-3b": LlamaConfig(
        vocab_size=128256,
        dim=3072,
        n_layers=28,
        n_heads=24,
        n_kv_heads=8,
        ffn_dim=8192,
        tie_embeddings=True,
        rope_scaling_factor=32.0,
        rope_original_max_seq=8192,
        max_seq_len=131072,
    ),
    # ~1.1B params — sized to fill a single v5e chip nicely at batch 64
    "bench-1b": LlamaConfig(
        vocab_size=32768,
        dim=2048,
        n_layers=16,
        n_heads=16,
        n_kv_heads=8,
        ffn_dim=8192,
    ),
    "mistral-7b": LlamaConfig(
        vocab_size=32000,
        dim=4096,
        n_layers=32,
        n_heads=32,
        n_kv_heads=8,
        ffn_dim=14336,
        rope_theta=10000.0,
        max_seq_len=8192,
    ),
    "qwen2.5-7b": LlamaConfig(
        vocab_size=152064,
        dim=3584,
        n_layers=28,
        n_heads=28,
        n_kv_heads=4,
        ffn_dim=18944,
        rope_theta=1000000.0,
        qkv_bias=True,
    ),
    "qwen2.5-0.5b": LlamaConfig(
        vocab_size=151936,
        dim=896,
        n_layers=24,
        n_heads=14,
        n_kv_heads=2,
        ffn_dim=4864,
        rope_theta=1000000.0,
        qkv_bias=True,
        tie_embeddings=True,
    ),
    # google/gemma-2b: MQA (1 kv head), GeGLU, (1+w) norms, scaled embeddings
    "gemma-2b": LlamaConfig(
        vocab_size=256000,
        dim=2048,
        n_layers=18,
        n_heads=8,
        n_kv_heads=1,
        ffn_dim=16384,
        rope_theta=10000.0,
        norm_eps=1e-6,
        tie_embeddings=True,
        hidden_act="gelu_tanh",
        norm_plus_one=True,
        embed_scale=True,
        head_dim_override=256,
    ),
    "gemma-7b": LlamaConfig(
        vocab_size=256000,
        dim=3072,
        n_layers=28,
        n_heads=16,
        n_kv_heads=16,
        ffn_dim=24576,
        rope_theta=10000.0,
        norm_eps=1e-6,
        tie_embeddings=True,
        hidden_act="gelu_tanh",
        norm_plus_one=True,
        embed_scale=True,
        head_dim_override=256,
    ),
    # google/gemma-2-2b: four-norm blocks, tanh soft-caps, GQA,
    # query_pre_attn_scalar = head_dim, alternating 4096-token local layers
    # (serve with max_ctx <= 4096; see LlamaConfig.sliding_window)
    "gemma2-2b": LlamaConfig(
        vocab_size=256000,
        dim=2304,
        n_layers=26,
        n_heads=8,
        n_kv_heads=4,
        ffn_dim=9216,
        rope_theta=10000.0,
        norm_eps=1e-6,
        tie_embeddings=True,
        hidden_act="gelu_tanh",
        norm_plus_one=True,
        embed_scale=True,
        head_dim_override=256,
        attn_logit_softcap=50.0,
        final_logit_softcap=30.0,
        post_norms=True,
        query_pre_attn_scalar=256.0,
        sliding_window=4096,
    ),
    "gemma2-9b": LlamaConfig(
        vocab_size=256000,
        dim=3584,
        n_layers=42,
        n_heads=16,
        n_kv_heads=8,
        ffn_dim=14336,
        rope_theta=10000.0,
        norm_eps=1e-6,
        tie_embeddings=True,
        hidden_act="gelu_tanh",
        norm_plus_one=True,
        embed_scale=True,
        head_dim_override=256,
        attn_logit_softcap=50.0,
        final_logit_softcap=30.0,
        post_norms=True,
        query_pre_attn_scalar=256.0,
        sliding_window=4096,
    ),
    # mistralai/Mixtral-8x7B(-Instruct): Mistral block + 8 top-2 experts
    "mixtral-8x7b": LlamaConfig(
        vocab_size=32000,
        dim=4096,
        n_layers=32,
        n_heads=32,
        n_kv_heads=8,
        ffn_dim=14336,
        rope_theta=1000000.0,
        max_seq_len=32768,
        n_experts=8,
        experts_per_token=2,
    ),
    # tiny MoE for CPU tests (4 experts, top-2)
    "moe-tiny": LlamaConfig(
        vocab_size=256,
        dim=64,
        n_layers=2,
        n_heads=4,
        n_kv_heads=2,
        ffn_dim=128,
        max_seq_len=128,
        rope_theta=10000.0,
        n_experts=4,
        experts_per_token=2,
        expert_capacity_factor=8.0,  # no drops: results batch-independent
        dtype=jnp.float32,
    ),
    # tiny config for CPU tests (matches an HF config in tests)
    "tiny": LlamaConfig(
        vocab_size=256,
        dim=64,
        n_layers=2,
        n_heads=4,
        n_kv_heads=2,
        ffn_dim=128,
        max_seq_len=128,
        rope_theta=10000.0,
        dtype=jnp.float32,
    ),
}


def init_params(config: LlamaConfig, key: jax.Array) -> dict:
    """Random init (serving benchmarks / tests); layout mirrors HF names."""
    c = config
    k_embed, k_layers, k_head = jax.random.split(key, 3)
    d, hd = c.dim, c.head_dim

    def norm_init(shape, scale):
        # truncated-normal-ish init; exact init only matters for training
        return (
            jax.random.normal(jax.random.fold_in(k_layers, hash(shape) % 2**31), shape)
            * scale
        ).astype(c.dtype)

    def stacked(shape, scale):
        return (
            jax.random.normal(
                jax.random.fold_in(k_layers, (hash(shape) + 1) % 2**31),
                (c.n_layers, *shape),
            )
            * scale
        ).astype(c.dtype)

    scale = d**-0.5
    if c.n_experts > 0:
        ffn = {
            "router": stacked((d, c.n_experts), scale),
            "w1": stacked((c.n_experts, d, c.ffn_dim), scale),
            "w3": stacked((c.n_experts, d, c.ffn_dim), scale),
            "w2": stacked((c.n_experts, c.ffn_dim, d), c.ffn_dim**-0.5),
        }
    else:
        ffn = {
            "w1": stacked((d, c.ffn_dim), scale),  # gate_proj
            "w3": stacked((d, c.ffn_dim), scale),  # up_proj
            "w2": stacked((c.ffn_dim, d), c.ffn_dim**-0.5),  # down_proj
        }
    params = {
        "embed": (jax.random.normal(k_embed, (c.vocab_size, d)) * scale).astype(c.dtype),
        "layers": {
            "ln1": jnp.ones((c.n_layers, d), dtype=c.dtype),
            "ln2": jnp.ones((c.n_layers, d), dtype=c.dtype),
            "wq": stacked((d, c.n_heads * hd), scale),
            "wk": stacked((d, c.n_kv_heads * hd), scale),
            "wv": stacked((d, c.n_kv_heads * hd), scale),
            "wo": stacked((c.n_heads * hd, d), scale),
            **ffn,
        },
        "norm": jnp.ones((d,), dtype=c.dtype),
    }
    if c.qkv_bias:
        params["layers"]["bq"] = jnp.zeros((c.n_layers, c.n_heads * hd), dtype=c.dtype)
        params["layers"]["bk"] = jnp.zeros((c.n_layers, c.n_kv_heads * hd), dtype=c.dtype)
        params["layers"]["bv"] = jnp.zeros((c.n_layers, c.n_kv_heads * hd), dtype=c.dtype)
    if c.post_norms:  # gemma-2 sublayer-output norms
        params["layers"]["ln1_post"] = jnp.ones((c.n_layers, d), dtype=c.dtype)
        params["layers"]["ln2_post"] = jnp.ones((c.n_layers, d), dtype=c.dtype)
    if not c.tie_embeddings:
        params["lm_head"] = (
            jax.random.normal(k_head, (d, c.vocab_size)) * scale
        ).astype(c.dtype)
    return params



def _embed(params: dict, tokens: jax.Array, c: LlamaConfig) -> jax.Array:
    x = params["embed"][tokens].astype(c.dtype)
    if c.embed_scale:  # gemma normalizes embeddings by sqrt(dim)
        x = x * jnp.asarray(c.dim**0.5, dtype=c.dtype)
    return x


def _final_norm_w(params: dict, c: LlamaConfig) -> jax.Array:
    return params["norm"] + 1.0 if c.norm_plus_one else params["norm"]


def _head_logits(x: jax.Array, params: dict, c: LlamaConfig) -> jax.Array:
    """lm_head projection -> float32 logits; applies gemma-2's final logit
    soft-capping when configured (cap * tanh(logits / cap))."""
    head = params["embed"].T if c.tie_embeddings else params["lm_head"]
    logits = (x @ head.astype(c.dtype)).astype(jnp.float32)
    if c.final_logit_softcap:
        cap = jnp.float32(c.final_logit_softcap)
        logits = cap * jnp.tanh(logits / cap)
    return logits


def _attn_mlp(
    x: jax.Array,  # [B, T, D]
    layer: dict,  # one layer's params (unstacked)
    config: LlamaConfig,
    positions: jax.Array,  # [B, T]
    attn_fn,
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Shared block body: returns (output, k, v) where k/v are this layer's
    new key/value tensors (for cache writes)."""
    from ..ops.quant import matmul as mm  # transparent int8 dequant

    c = config
    B, T, D = x.shape
    norm_w = (lambda w: w + 1.0) if c.norm_plus_one else (lambda w: w)
    if c.hidden_act == "silu":
        act = jax.nn.silu
    elif c.hidden_act == "gelu_tanh":
        act = partial(jax.nn.gelu, approximate=True)
    else:  # fail at trace time, not silently compute the wrong function
        raise ValueError(f"unsupported hidden_act {c.hidden_act!r} (silu|gelu_tanh)")
    h = rms_norm(x, norm_w(layer["ln1"]), c.norm_eps)
    q = mm(h, layer["wq"])
    k = mm(h, layer["wk"])
    v = mm(h, layer["wv"])
    if c.qkv_bias:
        q = q + layer["bq"]
        k = k + layer["bk"]
        v = v + layer["bv"]
    q = q.reshape(B, T, c.n_heads, c.head_dim)
    k = k.reshape(B, T, c.n_kv_heads, c.head_dim)
    v = v.reshape(B, T, c.n_kv_heads, c.head_dim)
    scaling = (
        (c.rope_scaling_factor, c.rope_low_freq_factor,
         c.rope_high_freq_factor, c.rope_original_max_seq)
        if c.rope_scaling_factor != 1.0
        else None
    )
    q = apply_rope(q, positions, c.rope_theta, scaling=scaling)
    k = apply_rope(k, positions, c.rope_theta, scaling=scaling)
    if c.query_pre_attn_scalar:
        # gemma-2 scales attention by 1/sqrt(query_pre_attn_scalar) instead
        # of 1/sqrt(head_dim); pre-scaling q here keeps every attention
        # implementation's internal 1/sqrt(head_dim) untouched
        q = q * jnp.asarray(
            (c.head_dim ** 0.5) / (c.query_pre_attn_scalar ** 0.5), dtype=q.dtype
        )
    attn = attn_fn(q, k, v)
    attn_out = mm(attn.reshape(B, T, c.n_heads * c.head_dim), layer["wo"])
    if c.post_norms:  # gemma-2: norm the sublayer OUTPUT before residual
        attn_out = rms_norm(attn_out, norm_w(layer["ln1_post"]), c.norm_eps)
    x = x + attn_out
    h = rms_norm(x, norm_w(layer["ln2"]), c.norm_eps)
    if c.n_experts > 0:
        from ..ops.moe import expert_capacity, moe_ffn

        cap = expert_capacity(
            min(B * T, c.moe_group_size),
            c.n_experts, c.experts_per_token, c.expert_capacity_factor,
        )
        y = moe_ffn(
            h.reshape(B * T, D),
            layer["router"],
            layer["w1"], layer["w3"], layer["w2"],
            experts_per_token=c.experts_per_token,
            capacity=cap,
            act=act,
            group_size=c.moe_group_size,
        )
        x = x + y.reshape(B, T, D)
    else:
        y = mm(act(mm(h, layer["w1"])) * mm(h, layer["w3"]), layer["w2"])
        if c.post_norms:
            y = rms_norm(y, norm_w(layer["ln2_post"]), c.norm_eps)
        x = x + y
    return x, k, v


def forward(
    params: dict,
    tokens: jax.Array,  # [B, T] int32
    config: LlamaConfig,
    positions: Optional[jax.Array] = None,  # [B, T]; default arange
    attn_impl=None,  # callable(q, k, v, positions) -> out; default dense causal
    remat: bool = False,
) -> jax.Array:
    """Full-sequence causal forward -> logits [B, T, V] (float32).

    ``attn_impl`` swaps the attention op — e.g. ring attention for
    sequence-parallel training (parallel.ring_attention). ``remat``
    rematerializes each layer in the backward pass (``jax.checkpoint`` on
    the scan body): activation memory drops from O(n_layers · B · T ·
    state) to one layer's worth at ~1/3 extra FLOPs — what lets an 8B
    train step fit HBM at real sequence lengths. Gradients are
    numerically identical (tested); inference paths leave it off (no
    backward = nothing to save)."""
    c = config
    B, T = tokens.shape
    if positions is None:
        positions = jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32), (B, T))
    if attn_impl is not None:
        if c.attn_logit_softcap:
            # refuse, don't mis-serve: a swapped-in attention op (ring
            # attention etc.) has no soft-cap path, and silently dropping
            # the cap trains/evaluates a DIFFERENT model than configured
            raise ValueError(
                "attn_logit_softcap is configured but a custom attn_impl "
                "cannot apply it — use the default dense attention (or a "
                "soft-cap-aware implementation) for gemma-2-style models"
            )
        attn = attn_impl
    else:
        attn = partial(causal_attention, softcap=c.attn_logit_softcap)

    def body(x, layer):
        out, _, _ = _attn_mlp(
            x,
            layer,
            c,
            positions,
            lambda q, k, v: attn(q, k, v, positions),
        )
        return out, None

    if remat:
        # prevent_cse=False: safe and faster under scan (the loop already
        # isolates iterations; CSE prevention only matters for unrolled use)
        body = jax.checkpoint(body, prevent_cse=False)

    x = _embed(params, tokens, c)

    x, _ = jax.lax.scan(body, x, params["layers"])
    x = rms_norm(x, _final_norm_w(params, c), c.norm_eps)
    return _head_logits(x, params, c)


# ---------------------------------------------------------------------------
# Serving: KV quantization plumbing (shared by the slot and paged layouts)
# ---------------------------------------------------------------------------
#
# A quantized cache is the SAME dict with int8 "k"/"v" plus per-row-per-head
# f32 scale arrays "ks"/"vs" shaped like the value arrays minus head_dim
# ([L, S, C, H_kv] slot / [L, NP, P, H_kv] paged). Presence of "ks" is the
# trace-time switch: every model program below commits through _kv_commit
# (quantize-on-commit, same single scatter) and reads through _kv_rows
# (dequantize-after-gather), so all compiled shapes — prefill, continuation,
# KV-only megastep chunks, decode, spec verify — serve quantized without a
# second code path. Scale scatters reuse the value scatter's leading
# indices, so scale storage is owned/freed with its pages by construction.


def _kv_scan_xs(cache: dict) -> tuple:
    """The read-only KV xs a layer scan carries: ``((k, ks?), (v, vs?))``
    tuples so quantized caches ride the same scan discipline."""
    if "ks" in cache:
        return (cache["k"], cache["ks"]), (cache["v"], cache["vs"])
    return (cache["k"],), (cache["v"],)


def _kv_rows(kv: tuple, idx, dtype) -> jax.Array:
    """Gather rows/pages from one layer's scanned KV leaf group and
    dequantize when quantized. ``idx`` is any indexer valid on the value
    array's leading dims (slice, gather array, block table)."""
    if len(kv) == 2:
        return kv_dequantize(kv[0][idx], kv[1][idx], dtype)
    return kv[0][idx].astype(dtype)


def _kv_commit(cache: dict, new_k: jax.Array, new_v: jax.Array, setter) -> dict:
    """Commit fresh K/V through ``setter(array, values)`` — the SAME
    scatter applied to the value arrays ([..., H_kv, d]) and, for a
    quantized cache, to the scale arrays ([..., H_kv]); quantization
    happens here, once per dispatch, on the already-stacked commit."""
    if "ks" in cache:
        qk, sk = kv_quantize(new_k)
        qv, sv = kv_quantize(new_v)
        return {
            "k": setter(cache["k"], qk),
            "v": setter(cache["v"], qv),
            "ks": setter(cache["ks"], sk),
            "vs": setter(cache["vs"], sv),
        }
    return {
        "k": setter(cache["k"], new_k.astype(cache["k"].dtype)),
        "v": setter(cache["v"], new_v.astype(cache["v"].dtype)),
    }


# ---------------------------------------------------------------------------
# Serving: slot KV cache
# ---------------------------------------------------------------------------


def init_kv_cache(
    config: LlamaConfig, max_slots: int, max_ctx: int, quantize_kv: bool = False
) -> dict:
    """[L, S, C, H_kv, d] per k/v, bf16 — or int8 plus [L, S, C, H_kv] f32
    scale rows with ``quantize_kv`` (see the KV quantization plumbing)."""
    c = config
    shape = (c.n_layers, max_slots, max_ctx, c.n_kv_heads, c.head_dim)
    if quantize_kv:
        return {
            "k": jnp.zeros(shape, dtype=jnp.int8),
            "v": jnp.zeros(shape, dtype=jnp.int8),
            "ks": jnp.zeros(shape[:-1], dtype=jnp.float32),
            "vs": jnp.zeros(shape[:-1], dtype=jnp.float32),
        }
    return {
        "k": jnp.zeros(shape, dtype=c.dtype),
        "v": jnp.zeros(shape, dtype=c.dtype),
    }


def prefill_batch(
    params: dict,
    cache: dict,
    tokens: jax.Array,  # [B, T] int32 (each row padded)
    lengths: jax.Array,  # [B] int32 — true prompt lengths
    slots: jax.Array,  # [B] int32 — distinct target slots
    config: LlamaConfig,
) -> tuple[dict, jax.Array]:
    """Run B prompts through the model in one dispatch, writing each row's
    K/V into its slot. Batching prefills is how burst admissions avoid
    serializing (one compiled program per (B, T) bucket pair; the engine
    splits admission groups into power-of-two B). Returns
    (cache, logits_at_last_token [B, V])."""
    c = config
    B, T = tokens.shape
    ar = jnp.arange(T)
    positions = jnp.where(ar[None, :] < lengths[:, None], ar[None, :], -1)  # [B,T]
    x = _embed(params, tokens, c)  # [B, T, D]

    def body(carry, layer):
        x = carry
        out, k, v = _attn_mlp(
            x,
            layer,
            c,
            positions,
            lambda q, k, v: blocked_causal_attention(
                q, k, v, positions, softcap=c.attn_logit_softcap
            ),
        )
        return out, (k, v)

    # prompt attention never reads the cache, so the cache stays OUT of the
    # scan entirely: stack the per-layer K/V (ys) and commit with one
    # scatter — writing inside the scan would copy the whole cache per layer
    # (see decode_step)
    x, (new_k, new_v) = jax.lax.scan(body, x, params["layers"])
    cache = _kv_commit(
        cache, new_k, new_v, lambda arr, val: arr.at[:, slots, :T].set(val)
    )
    # (padded tail is garbage but never read: decode masks by seq_len)
    x = rms_norm(x, _final_norm_w(params, c), c.norm_eps)
    last = x[jnp.arange(B), lengths - 1]  # [B, D]
    logits = _head_logits(last, params, c)
    return cache, logits


def prefill(
    params: dict,
    cache: dict,
    tokens: jax.Array,  # [T] int32 (padded)
    length: jax.Array,  # scalar int32 — true prompt length
    slot: jax.Array,  # scalar int32
    config: LlamaConfig,
) -> tuple[dict, jax.Array]:
    """Single-prompt prefill (B=1 view of :func:`prefill_batch`)."""
    cache, logits = prefill_batch(
        params, cache, tokens[None], length[None], slot[None], config
    )
    return cache, logits[0]


def _continue_forward(
    params: dict,
    cache: dict,
    tokens: jax.Array,  # [B, T] int32 — SUFFIX tokens (rows padded)
    lengths: jax.Array,  # [B] int32 — true suffix lengths
    starts: jax.Array,  # [B] int32 — absolute position of each suffix start
    slots: jax.Array,  # [B] int32
    config: LlamaConfig,
) -> tuple[dict, jax.Array]:
    """Shared continuation body (slot layout): the first ``starts[b]``
    positions of each slot's KV rows are already populated; run only the
    suffix through the model, attending over prefix + suffix, and commit the
    suffix K/V. Returns ``(cache, x_normed [B, T, D])`` — the final-norm
    hidden states at EVERY suffix position, so callers choose the head:
    :func:`prefill_continue` projects only the last token (prefix-cache
    hits / chunked prefill), :func:`verify_continue` projects all positions
    (speculative verification)."""
    c = config
    B, T = tokens.shape
    ar = jnp.arange(T)
    positions = jnp.where(ar[None, :] < lengths[:, None], starts[:, None] + ar[None, :], -1)
    x = _embed(params, tokens, c)
    C = cache["k"].shape[2]
    # scatter indices for the suffix writes; clamped so bucket padding can
    # never write past the row (clamped garbage lands at C-1, which is
    # never readable: attention masks at seq_len, and a slot finishes
    # before its seq_len reaches C)
    write_pos = jnp.minimum(starts[:, None] + ar[None, :], C - 1)  # [B, T]

    # keys = [prefix rows (read-only, positions < start) ++ own suffix];
    # the cache's stale suffix region is masked via key position -1
    cache_pos = jnp.where(
        jnp.arange(C)[None, :] < starts[:, None], jnp.arange(C)[None, :], -1
    )  # [B, C]
    key_pos = jnp.concatenate([cache_pos, positions], axis=1)  # [B, C+T]

    def body(carry, scanned):
        x = carry
        layer, k_kv, v_kv = scanned  # read-only (value + optional scales)

        def attn(q, k, v):
            k_full = jnp.concatenate(
                [_kv_rows(k_kv, slots, k.dtype), k], axis=1
            )
            v_full = jnp.concatenate(
                [_kv_rows(v_kv, slots, v.dtype), v], axis=1
            )
            out = continue_attention(
                q, k_full, v_full, positions, key_pos,
                softcap=c.attn_logit_softcap,
            )
            attn.new_kv = (k, v)
            return out

        out, _, _ = _attn_mlp(x, layer, c, positions, attn)
        return out, attn.new_kv

    x, (new_k, new_v) = jax.lax.scan(
        body, x, (params["layers"], *_kv_scan_xs(cache))
    )
    # one scatter commits the suffix K/V for every layer
    cache = _kv_commit(
        cache, new_k, new_v,
        lambda arr, val: arr.at[:, slots[:, None], write_pos].set(val),
    )
    x = rms_norm(x, _final_norm_w(params, c), c.norm_eps)
    return cache, x


def prefill_continue(
    params: dict,
    cache: dict,
    tokens: jax.Array,  # [B, T] int32 — SUFFIX tokens (rows padded)
    lengths: jax.Array,  # [B] int32 — true suffix lengths
    starts: jax.Array,  # [B] int32 — absolute position of each suffix start
    slots: jax.Array,  # [B] int32
    config: LlamaConfig,
) -> tuple[dict, jax.Array]:
    """Prefix-cache continuation: the first ``starts[b]`` positions of each
    slot's KV rows were already populated (copied from the prefix cache);
    run only the suffix through the model, attending over prefix + suffix.
    Costs O(suffix) model FLOPs instead of O(full prompt) — the win that
    makes multi-turn agent conversations cheap (each turn's prompt extends
    the previous one). Returns (cache, last-token logits [B, V])."""
    B = tokens.shape[0]
    cache, x = _continue_forward(params, cache, tokens, lengths, starts, slots, config)
    last = x[jnp.arange(B), lengths - 1]
    logits = _head_logits(last, params, config)
    return cache, logits


def prefill_continue_kv(
    params: dict,
    cache: dict,
    tokens: jax.Array,  # [B, T] int32 — chunk tokens (rows padded)
    lengths: jax.Array,  # [B] int32 — true chunk lengths (0 = padding lane)
    starts: jax.Array,  # [B] int32 — absolute chunk start per row
    slots: jax.Array,  # [B] int32
    config: LlamaConfig,
) -> dict:
    """KV-only continuation (the fused megastep's mid-chunk phase): the
    exact cache writes of :func:`prefill_continue` with the lm_head
    projection dropped — non-final chunks never sample, so the split
    path's discarded logits were pure waste. A padding lane (length 0,
    start = max_ctx) clamps its garbage write to the never-readable last
    row (see ``_continue_forward``'s write clamp)."""
    cache, _x = _continue_forward(
        params, cache, tokens, lengths, starts, slots, config
    )
    return cache


def verify_continue(
    params: dict,
    cache: dict,
    tokens: jax.Array,  # [B, T] int32 — last sampled token + draft (rows padded)
    lengths: jax.Array,  # [B] int32 — 1 + draft length per row
    starts: jax.Array,  # [B] int32 — seq_len per row (first unwritten KV position)
    config: LlamaConfig,
) -> tuple[dict, jax.Array]:
    """Speculative-decode verify pass (slot layout): score EVERY draft
    position in one dispatch. Row ``b`` IS decode lane/slot ``b`` (the spec
    path always dispatches the compacted width, so no slot indirection is
    needed). Same attention/KV-write semantics as :func:`prefill_continue`;
    the only difference is the head: logits at ALL positions [B, T, V], so
    ``logits[b, i]`` scores the token following ``tokens[b, i]`` — exactly
    what :func:`agentcontrolplane_tpu.ops.sampling.speculative_accept`
    consumes. KV for the whole row is written optimistically; a rejected
    tail needs no rollback because the engine only advances ``seq_len`` over
    the accepted prefix and attention never reads beyond it."""
    B = tokens.shape[0]
    cache, x = _continue_forward(
        params, cache, tokens, lengths, starts, jnp.arange(B), config
    )
    return cache, _head_logits(x, params, config)


# ---------------------------------------------------------------------------
# Serving: paged KV cache (page tables; ops.paged + ops.pallas)
# ---------------------------------------------------------------------------


def init_paged_cache(
    config: LlamaConfig, num_pages: int, page_size: int, quantize_kv: bool = False
) -> dict:
    from ..ops.paged import init_kv_pages

    return init_kv_pages(
        config.n_layers, num_pages, page_size, config.n_kv_heads, config.head_dim,
        config.dtype, quantize=quantize_kv,
    )


def prefill_paged_batch(
    params: dict,
    pages: dict,  # {"k": [L, num_pages, P, H_kv, d], "v": ...}
    tokens: jax.Array,  # [B, T] int32 (rows padded to a multiple of page_size)
    lengths: jax.Array,  # [B] int32
    page_ids: jax.Array,  # [B, T // P] int32 (TRASH_PAGE beyond each prompt)
    config: LlamaConfig,
) -> tuple[dict, jax.Array]:
    """B prompts forward in one dispatch, each writing K/V into its own
    pages. Rows' trash-page writes may collide — unordered garbage into the
    never-read page 0."""
    c = config
    B, T = tokens.shape
    ar = jnp.arange(T)
    positions = jnp.where(ar[None, :] < lengths[:, None], ar[None, :], -1)
    x = _embed(params, tokens, c)

    def body(carry, layer):
        x = carry
        out, k, v = _attn_mlp(
            x, layer, c, positions,
            lambda q, k, v: blocked_causal_attention(
                q, k, v, positions, softcap=c.attn_logit_softcap
            ),
        )
        return out, (k, v)

    # pages stay out of the scan (prompt attention never reads them); one
    # scatter commits all layers' blocks — see prefill_batch/decode_step
    x, (new_k, new_v) = jax.lax.scan(body, x, params["layers"])
    pages = _commit_whole_pages(pages, new_k, new_v, page_ids)
    x = rms_norm(x, _final_norm_w(params, c), c.norm_eps)
    last = x[jnp.arange(B), lengths - 1]
    logits = _head_logits(last, params, c)
    return pages, logits


def prefill_paged(
    params: dict,
    pages: dict,
    tokens: jax.Array,  # [T] int32 (padded to a multiple of page_size)
    length: jax.Array,  # scalar int32
    page_ids: jax.Array,  # [T // P] int32 (TRASH_PAGE beyond the prompt)
    config: LlamaConfig,
) -> tuple[dict, jax.Array]:
    """Single-prompt paged prefill (B=1 view of :func:`prefill_paged_batch`)."""
    pages, logits = prefill_paged_batch(
        params, pages, tokens[None], length[None], page_ids[None], config
    )
    return pages, logits[0]


def _paged_continue_forward(
    params: dict,
    pages: dict,  # {"k": [L, num_pages, P, H_kv, d], "v": ...}
    tokens: jax.Array,  # [B, T] int32 — new tokens (rows padded)
    lengths: jax.Array,  # [B] int32 — true token counts
    starts: jax.Array,  # [B] int32 — absolute position of each row's first token
    block_tables: jax.Array,  # [B, max_pages] int32
    config: LlamaConfig,
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Shared paged continuation body: run each row's new tokens through the
    model attending over its gathered prefix pages (positions < start) plus
    the new tokens themselves. Returns ``(new_k, new_v, x_normed)`` with
    ``new_k/new_v`` [L, B, T, H_kv, d] UNCOMMITTED — the callers commit
    differently: :func:`prefill_paged_continue` writes whole (page-aligned,
    fresh) pages, :func:`verify_paged_continue` scatters per token because a
    draft starts mid-page, inside a page holding live prefix KV."""
    c = config
    B, T = tokens.shape
    ar = jnp.arange(T)
    positions = jnp.where(ar[None, :] < lengths[:, None], starts[:, None] + ar[None, :], -1)
    x = _embed(params, tokens, c)
    max_pages = block_tables.shape[1]

    P = pages["k"].shape[2]
    # keys = [gathered prefix pages (positions < start) ++ own suffix]; the
    # suffix pages referenced by the block table are not yet written, so
    # their gathered rows are stale — masked via key position -1.
    # OFFSET-MAJOR row order: gathered pages are transposed to [P, M]
    # before the merge so the within-page axis — which carries the mesh's
    # 'sp' axis under context-parallel serving — stays OUTERMOST. Merging
    # with the sharded axis inner is not GSPMD-representable and would
    # all-gather the page pool; outermost, the merged ctx dim stays
    # contiguously sp-sharded (same shape as the slot path's sharded C).
    r_idx = jnp.arange(P * max_pages)
    row_pos = (r_idx % max_pages) * P + r_idx // max_pages  # abs ctx position
    cache_pos = jnp.where(
        row_pos[None, :] < starts[:, None], row_pos[None, :], -1
    )  # [B, P*M]
    key_pos = jnp.concatenate([cache_pos, positions], axis=1)

    def body(carry, scanned):
        x = carry
        layer, k_kv, v_kv = scanned  # read-only (value + optional scales)

        def attn(q, k, v):
            # gather (+ dequantize) each row's pages, then transpose to the
            # offset-major row order described above
            k_gath = _kv_rows(k_kv, block_tables, k.dtype)  # [B, M, P, H, d]
            v_gath = _kv_rows(v_kv, block_tables, v.dtype)
            k_rows = jnp.swapaxes(k_gath, 1, 2).reshape(
                B, P * max_pages, *k_gath.shape[3:]
            )
            v_rows = jnp.swapaxes(v_gath, 1, 2).reshape(
                B, P * max_pages, *v_gath.shape[3:]
            )
            k_full = jnp.concatenate([k_rows, k], axis=1)
            v_full = jnp.concatenate([v_rows, v], axis=1)
            out = continue_attention(
                q, k_full, v_full, positions, key_pos,
                softcap=c.attn_logit_softcap,
            )
            attn.new_kv = (k, v)
            return out

        out, _, _ = _attn_mlp(x, layer, c, positions, attn)
        return out, attn.new_kv

    x, (new_k, new_v) = jax.lax.scan(
        body, x, (params["layers"], *_kv_scan_xs(pages))
    )
    x = rms_norm(x, _final_norm_w(params, c), c.norm_eps)
    return new_k, new_v, x


def prefill_paged_continue(
    params: dict,
    pages: dict,  # {"k": [L, num_pages, P, H_kv, d], "v": ...}
    tokens: jax.Array,  # [B, T] int32 — SUFFIX tokens (rows padded)
    lengths: jax.Array,  # [B] int32 — true suffix lengths
    starts: jax.Array,  # [B] int32 — absolute suffix start (page-aligned)
    page_ids: jax.Array,  # [B, T // P] int32 — the SUFFIX pages
    block_tables: jax.Array,  # [B, max_pages] int32 — prefix + suffix pages
    config: LlamaConfig,
) -> tuple[dict, jax.Array]:
    """Paged prefix-cache continuation: the prefix pages referenced by each
    row's block table are already populated (SHARED with the cache entry —
    never written here; starts are page-aligned so suffix writes only touch
    fresh pages). Runs the suffix through the model, attending over the
    gathered prefix+suffix pages. Returns (pages, last-token logits [B, V])."""
    B = tokens.shape[0]
    new_k, new_v, x = _paged_continue_forward(
        params, pages, tokens, lengths, starts, block_tables, config
    )
    # one scatter commits the suffix blocks for every layer
    pages = _commit_whole_pages(pages, new_k, new_v, page_ids)
    last = x[jnp.arange(B), lengths - 1]
    logits = _head_logits(last, params, config)
    return pages, logits


def _commit_whole_pages(
    pages: dict,
    new_k: jax.Array,  # [L, B, T, H_kv, d]
    new_v: jax.Array,
    page_ids: jax.Array,  # [B, T // P] int32
) -> dict:
    """Whole-page commit shared by the batch prefill, the split
    continuation, and the fused megastep's mid-chunk phase — one copy of
    the page-write discipline, so the paths' KV layout can never silently
    diverge. The blocks reshape generalizes to the scale arrays (values
    [L, B, T, H, d] and scales [L, B, T, H] both split T into pages)."""
    L = new_k.shape[0]
    B, T = new_k.shape[1], new_k.shape[2]
    P = pages["k"].shape[2]
    blocks = lambda t: t.reshape(L, B * (T // P), P, *t.shape[3:])
    flat_ids = page_ids.reshape(-1)
    return _kv_commit(
        pages, new_k, new_v,
        lambda arr, val: arr.at[:, flat_ids].set(blocks(val)),
    )


def prefill_paged_continue_kv(
    params: dict,
    pages: dict,  # {"k": [L, num_pages, P, H_kv, d], "v": ...}
    tokens: jax.Array,  # [B, T] int32 — chunk tokens (rows padded)
    lengths: jax.Array,  # [B] int32 — true chunk lengths (0 = padding lane)
    starts: jax.Array,  # [B] int32 — absolute chunk start (page-aligned)
    page_ids: jax.Array,  # [B, T // P] int32 — the chunk's pages (TRASH pads)
    block_tables: jax.Array,  # [B, max_pages] int32
    config: LlamaConfig,
) -> dict:
    """Paged KV-only continuation (the fused megastep's mid-chunk phase):
    :func:`prefill_paged_continue`'s whole-page commit without the lm_head
    projection. Padding lanes route every page write to the trash page."""
    new_k, new_v, _x = _paged_continue_forward(
        params, pages, tokens, lengths, starts, block_tables, config
    )
    return _commit_whole_pages(pages, new_k, new_v, page_ids)


def verify_paged_continue(
    params: dict,
    pages: dict,  # {"k": [L, num_pages, P, H_kv, d], "v": ...}
    tokens: jax.Array,  # [B, T] int32 — last sampled token + draft (rows padded)
    lengths: jax.Array,  # [B] int32 — 1 + draft length per row
    starts: jax.Array,  # [B] int32 — seq_len per row (NOT page-aligned)
    block_tables: jax.Array,  # [B, max_pages] int32
    config: LlamaConfig,
) -> tuple[dict, jax.Array]:
    """Speculative-decode verify pass (paged layout): score every draft
    position in one dispatch over the gathered block-table pages. Unlike
    :func:`prefill_paged_continue`, the rows start MID-PAGE (``starts`` is
    the slot's live seq_len), so the commit scatters per token via
    :func:`agentcontrolplane_tpu.ops.paged.token_write_targets` — a page-
    granular write would clobber the live prefix KV sharing the first page.
    Padded positions land on the trash page. Returns (pages, logits
    [B, T, V]); the rejected tail's KV needs no rollback (attention masks
    by seq_len, which the engine only advances over the accepted prefix)."""
    from ..ops.paged import token_write_targets

    B, T = tokens.shape
    P = pages["k"].shape[2]
    new_k, new_v, x = _paged_continue_forward(
        params, pages, tokens, lengths, starts, block_tables, config
    )
    target, offset = token_write_targets(block_tables, starts, lengths, P, T)
    pages = _kv_commit(
        pages, new_k, new_v,
        lambda arr, val: arr.at[:, target, offset].set(val),
    )
    return pages, _head_logits(x, params, config)


def decode_step_paged(
    params: dict,
    pages: dict,
    tokens: jax.Array,  # [S] int32
    seq_lens: jax.Array,  # [S] int32 (length before this token)
    block_tables: jax.Array,  # [S, max_pages] int32
    active: jax.Array,  # [S] bool
    config: LlamaConfig,
    use_pallas: bool = False,
    mesh=None,  # required for the pallas path when the mesh has tp > 1
) -> tuple[dict, jax.Array]:
    """One decode step for all slots against the paged cache.

    Same HBM discipline as :func:`decode_step`: pages ride the layer scan
    READ-ONLY, the new token attends via a self term (folded outside the
    Pallas kernel from its unnormalized (acc, m, l) output), and one
    scatter after the scan commits every layer's new K/V to the pages."""
    from ..ops.paged import (
        TRASH_PAGE,
        paged_decode_attention_reference_cache_plus_new,
    )

    c = config
    S = tokens.shape[0]
    positions = seq_lens[:, None]
    x = _embed(params, tokens[:, None], c)
    quantized = "ks" in pages
    tp_size = sp_size = 1
    if mesh is not None:
        axes = dict(zip(mesh.axis_names, mesh.devices.shape))
        tp_size = axes.get("tp", 1)
        sp_size = axes.get("sp", 1)

    def body(carry, scanned):
        x = carry
        layer, k_kv, v_kv = scanned  # read-only (value + optional scales)
        k_pages_l, v_pages_l = k_kv[0], v_kv[0]
        # int8 pages carry f32 scale twins; the Pallas path DMAs them with
        # each page fetch and dequantizes in VMEM (same formula as the
        # reference, so the parity pin holds bit-for-bit in f32)
        k_scales_l = k_kv[1] if quantized else None
        v_scales_l = v_kv[1] if quantized else None

        def attn(q, k, v):
            if use_pallas and (tp_size > 1 or sp_size > 1):
                # the sharded wrapper routes sp>1 meshes through the
                # cross-rank (acc, m, l) flash merge
                from ..ops.pallas.paged_attention import (
                    paged_decode_attention_cache_plus_new_sharded,
                )

                out = paged_decode_attention_cache_plus_new_sharded(
                    mesh, q[:, 0], k_pages_l, v_pages_l, block_tables, seq_lens,
                    k[:, 0], v[:, 0],
                    k_scales=k_scales_l, v_scales=v_scales_l,
                )
            elif use_pallas:
                from ..ops.pallas.paged_attention import (
                    paged_decode_attention_cache_plus_new,
                )

                out = paged_decode_attention_cache_plus_new(
                    q[:, 0], k_pages_l, v_pages_l, block_tables, seq_lens,
                    k[:, 0], v[:, 0],
                    k_scales=k_scales_l, v_scales=v_scales_l,
                )
            else:
                out = paged_decode_attention_reference_cache_plus_new(
                    q[:, 0], k_pages_l, v_pages_l, block_tables, seq_lens,
                    k[:, 0], v[:, 0],
                    k_scales=k_kv[1] if quantized else None,
                    v_scales=v_kv[1] if quantized else None,
                )
            attn.new_kv = (k[:, 0], v[:, 0])
            return out[:, None]

        out, _, _ = _attn_mlp(x, layer, c, positions, attn)
        return out, attn.new_kv

    x, (new_k, new_v) = jax.lax.scan(
        body, x, (params["layers"], *_kv_scan_xs(pages))
    )
    # one scatter commits all layers: (l, page(slot), offset(slot)); inactive
    # slots land on the trash page
    P = pages["k"].shape[2]
    page_idx = seq_lens // P
    offset = seq_lens % P
    target = block_tables[jnp.arange(S), page_idx]
    target = jnp.where(active, target, TRASH_PAGE)
    pages = _kv_commit(
        pages, new_k, new_v,
        lambda arr, val: arr.at[:, target, offset].set(val),
    )
    x = rms_norm(x[:, 0], _final_norm_w(params, c), c.norm_eps)
    logits = _head_logits(x, params, c)
    return pages, logits


def decode_step(
    params: dict,
    cache: dict,
    tokens: jax.Array,  # [W] int32 — last sampled token per slot, W <= max_slots
    seq_lens: jax.Array,  # [W] int32 — current length per slot (before this token)
    config: LlamaConfig,
    active: Optional[jax.Array] = None,  # [W] bool; inactive lanes write to C-1
) -> tuple[dict, jax.Array]:
    """One decode step for slots 0..W-1 (the continuous-batching hot loop).
    W may be narrower than the cache's slot count — width bucketing: at low
    occupancy the engine dispatches a power-of-two W covering the active
    slots, so one live request doesn't pay max_slots of compute. Inactive
    slots inside W compute garbage that is never read; cache rows beyond W
    pass through untouched. Returns (cache, logits [W, V]).

    ``active`` masks the K/V WRITE for inactive lanes to the never-readable
    row C-1 (attention masks at seq_len, and a lane deactivates before its
    seq_len reaches C — the same clamp the verify dispatch uses for its
    absent lanes). Without it an inactive lane writes garbage at its stale
    uploaded ``seq_lens`` — harmless for a free lane (row 0, overwritten by
    the next prefill) but CORRUPTING for a mid-prefill slot below the
    dispatch width, whose chunk loop has already written real prompt KV at
    that position. The split dispatch path mostly dodged this by accident
    (chunking slots usually sit above the active width; finals re-upload
    lanes before the block); the fused megastep's decode phase runs on
    pre-final lanes and hit it deterministically. Paged decode always had
    the equivalent mask (inactive targets -> TRASH_PAGE).

    HBM discipline (measured on v5e through the hot loop): the cache rides
    the layer scan as READ-ONLY xs, the new token attends via an explicit
    self term (decode_attention_cache_plus_new), and all L layers' new K/V
    commit in ONE scatter after the scan. Writing inside the scan — whether
    as stacked ys or as a scatter on a carried cache — makes XLA's copy
    insertion duplicate the entire cache every step (44ms/step vs 13.5 for
    this form at bench-1b 64x512)."""
    c = config
    W = tokens.shape[0]
    positions = seq_lens[:, None]  # the new token's position, [W, 1]
    x = _embed(params, tokens[:, None], c)  # [W, 1, D]

    def body(carry, scanned):
        x = carry
        layer, k_kv, v_kv = scanned  # cache rows: read-only (+ scales)

        def attn(q, k, v):
            out = decode_attention_cache_plus_new(
                q[:, 0],
                _kv_rows(k_kv, slice(0, W), k.dtype),
                _kv_rows(v_kv, slice(0, W), v.dtype),
                k[:, 0], v[:, 0], seq_lens,
                softcap=c.attn_logit_softcap,
            )
            attn.new_kv = (k[:, 0], v[:, 0])
            return out[:, None]

        out, _, _ = _attn_mlp(x, layer, c, positions, attn)
        return out, attn.new_kv

    x, (new_k, new_v) = jax.lax.scan(
        body, x, (params["layers"], *_kv_scan_xs(cache))
    )
    # one scatter commits every layer's token: rows (l, s, seq_lens[s]);
    # inactive lanes clamp to the never-read last row
    slot_idx = jnp.arange(W)
    C = cache["k"].shape[2]
    write_rows = (
        jnp.where(active, seq_lens, C - 1) if active is not None else seq_lens
    )
    cache = _kv_commit(
        cache, new_k, new_v,
        lambda arr, val: arr.at[:, slot_idx, write_rows].set(val),
    )
    x = rms_norm(x[:, 0], _final_norm_w(params, c), c.norm_eps)  # [S, D]
    logits = _head_logits(x, params, c)
    return cache, logits
