"""Version compatibility shims for the pinned accelerator stack.

The codebase targets the modern ``jax.shard_map`` API (``check_vma``,
``axis_names``); older jax (< 0.5, e.g. the 0.4.37 this container pins)
only ships ``jax.experimental.shard_map.shard_map`` with the
``check_rep``/``auto`` spelling. Installing the translation at package
import keeps every call site on the one modern spelling instead of
scattering try/except fallbacks through kernels.
"""

from __future__ import annotations

import jax


def install_shard_map_compat() -> None:
    """Alias ``jax.shard_map`` on jax versions that predate it.

    Translation: ``check_vma`` -> ``check_rep``; ``axis_names`` (the axes
    the body is MANUAL over) -> ``auto`` (its complement over the mesh's
    axes). No-op when jax already provides ``jax.shard_map``.
    """
    if hasattr(jax, "shard_map"):
        return
    from jax.experimental.shard_map import shard_map as _shard_map

    def shard_map(f, *, mesh, in_specs, out_specs, check_vma=True, axis_names=None):
        if axis_names is None:
            auto = frozenset()
        else:
            auto = frozenset(mesh.axis_names) - frozenset(axis_names)
        return _shard_map(
            f,
            mesh=mesh,
            in_specs=in_specs,
            out_specs=out_specs,
            check_rep=check_vma,
            auto=auto,
        )

    jax.shard_map = shard_map


install_shard_map_compat()
