"""Operator composition — wires the whole control plane.

Equivalent of ``acp/cmd/main.go:68-327``: build the manager, register all six
controllers with a shared MCPManager and tracer, attach the REST server as a
leader-gated runnable, and start. The TPU engine (when configured) is started
here too and handed to the LLM client factory as the ``provider: tpu``
backend.
"""

from __future__ import annotations

import asyncio
from dataclasses import dataclass
from typing import Optional

from .controllers import (
    AgentReconciler,
    ContactChannelReconciler,
    LLMReconciler,
    MCPServerReconciler,
    TaskReconciler,
    ToolCallReconciler,
)
from .humanlayer import (
    HumanLayerClientFactory,
    LocalHumanBackend,
    LocalHumanLayerClientFactory,
)
from .kernel import Manager, RemoteStore, SqliteBackend, Store, StoreServer
from .kernel.runtime import map_owner
from .llmclient import DefaultLLMClientFactory, LLMClientFactory
from .mcp import MCPManager
from .observability import MetricsExporter, NOOP_TRACER, Tracer


@dataclass
class OperatorOptions:
    db_path: Optional[str] = None  # None = in-memory store
    # Multi-replica control plane (the reference's N-pods-one-apiserver
    # topology, cmd/main.go:213-226 + docs/distributed-locking.md):
    # store_address connects this replica to another replica's served store
    # (unix:///path or tcp://host:port) instead of owning one; serve_store
    # makes THIS replica serve its store at the given address so others can
    # join. With a shared store, task-llm leases and leader election hold
    # across processes — a surviving replica adopts a dead one's tasks.
    store_address: Optional[str] = None
    serve_store: Optional[str] = None
    # Shared secret for the served-store socket, both sides: the serving
    # replica requires it from every client, a joining replica presents it.
    # Empty = no auth (unix:// 0600 sockets or isolated loopback only).
    store_token: str = ""
    identity: str = "acp-tpu-0"
    leader_election: bool = False
    api_port: int = 8082
    # bind address; 127.0.0.1 for local dev, 0.0.0.0 inside a container
    # (deploy/Dockerfile) where loopback is unreachable from outside
    api_host: str = "127.0.0.1"
    # non-empty = require "Authorization: Bearer <token>" on every REST route
    # except health probes (reference posture: acp/cmd/main.go:167-206)
    api_token: str = ""
    # TLS serving posture (reference: cert-watcher-fed TLS options for the
    # webhook/metrics servers, acp/cmd/main.go:118-166). cert+key => HTTPS;
    # client_ca additionally demands verified client certs (mTLS). Cert/key
    # files are re-loaded on change while serving (cert-watcher parity), so
    # rotation needs no restart.
    tls_cert_path: Optional[str] = None
    tls_key_path: Optional[str] = None
    tls_client_ca_path: Optional[str] = None
    enable_rest: bool = True
    llm_probe: bool = True
    verify_channel_credentials: bool = True
    engine: object | None = None  # engine.Engine for provider: tpu
    # fleet.FleetRouter: when set, the chat paths and the LLM client
    # factory submit through the router (pool of engines) instead of a
    # single engine; /v1/fleet serves its stats. The router duck-types
    # the Engine submit surface, so everything downstream is unchanged.
    fleet: object | None = None
    # Reconcile concurrency for the two hot controllers. A Task worker spends
    # almost all its time awaiting the LLM send, so the worker count bounds how
    # many requests the continuous-batching engine can see at once — 4 workers
    # over 16 simultaneous Tasks means 4 serialized waves of prefill+decode.
    # Size it to the engine's slot count, not to CPU parallelism (workers are
    # coroutines; controller-runtime's MaxConcurrentReconciles equivalent).
    task_workers: int = 32
    toolcall_workers: int = 16


class Operator:
    def __init__(
        self,
        options: OperatorOptions | None = None,
        store: Optional[Store] = None,
        llm_factory: Optional[LLMClientFactory] = None,
        hl_factory: Optional[HumanLayerClientFactory] = None,
        tracer: Optional[Tracer] = None,
    ):
        self.options = options or OperatorOptions()
        if store is None and self.options.store_address:
            store = RemoteStore(
                self.options.store_address, token=self.options.store_token or None
            )
        self.store = store or Store(
            SqliteBackend(self.options.db_path) if self.options.db_path else None
        )
        self.store_server: Optional[StoreServer] = None
        if self.options.serve_store:
            if not isinstance(self.store, Store):
                raise ValueError("serve_store requires this replica to own a local Store")
            self.store_server = StoreServer(
                self.store,
                self.options.serve_store,
                token=self.options.store_token or None,
            )
        self.tracer = tracer or Tracer()
        self.mcp_manager = MCPManager(self.store)
        self.human_backend = LocalHumanBackend()
        self.hl_factory = hl_factory or LocalHumanLayerClientFactory(self.human_backend)
        if isinstance(self.hl_factory, LocalHumanLayerClientFactory):
            self.human_backend = self.hl_factory.backend
        self.engine = self.options.engine
        self.fleet = self.options.fleet
        if self.engine is not None:
            # flight-recorder OTLP linkage: finished requests' phase
            # windows export as child spans through the operator's tracer
            # (plain attribute replacement; None stays span-less)
            self.engine.flight.tracer = self.tracer  # type: ignore[attr-defined]
        # the fleet router outranks a bare engine as the serving handle:
        # it duck-types the submit surface, so the factory and the REST
        # chat paths route pool-wide without knowing the difference
        self.llm_factory = llm_factory or DefaultLLMClientFactory(
            engine=self.fleet if self.fleet is not None else self.engine
        )

        self.manager = Manager(
            self.store,
            identity=self.options.identity,
            leader_election=self.options.leader_election,
        )
        self.task_reconciler = TaskReconciler(
            store=self.store,
            recorder=self.manager.recorder,
            llm_factory=self.llm_factory,
            mcp_manager=self.mcp_manager,
            hl_factory=self.hl_factory,
            tracer=self.tracer,
            identity=self.options.identity,
        )
        self.toolcall_reconciler = ToolCallReconciler(
            store=self.store,
            recorder=self.manager.recorder,
            mcp_manager=self.mcp_manager,
            hl_factory=self.hl_factory,
            tracer=self.tracer,
        )
        self._register_controllers()
        # OTLP metrics push alongside traces (internal/otel/otel.go:58-80
        # parity); silent no-op unless OTEL_EXPORTER_OTLP_ENDPOINT is set
        self.metrics_exporter = MetricsExporter()
        self.rest_server = None
        if self.options.enable_rest:
            from .server.rest import RestServer

            self.rest_server = RestServer(self, host=self.options.api_host)
            self.manager.add_runnable(
                self.rest_server.run, leader_gated=self.options.leader_election
            )

    def _register_controllers(self) -> None:
        m = self.manager
        self.llm_reconciler = LLMReconciler(
            self.store, m.recorder, self.llm_factory, probe=self.options.llm_probe
        )
        self.contactchannel_reconciler = ContactChannelReconciler(
            self.store,
            m.recorder,
            self.hl_factory,
            verify_credentials=self.options.verify_channel_credentials,
        )
        self.mcpserver_reconciler = MCPServerReconciler(
            self.store, m.recorder, self.mcp_manager
        )
        self.agent_reconciler = AgentReconciler(self.store, m.recorder)
        m.add_controller("llm", "LLM", self.llm_reconciler)
        m.add_controller("contactchannel", "ContactChannel", self.contactchannel_reconciler)
        m.add_controller("mcpserver", "MCPServer", self.mcpserver_reconciler)
        # Agents with pending deps self-requeue every 5s (the reference's
        # polling pattern), so no dependency watch wiring is needed.
        m.add_controller("agent", "Agent", self.agent_reconciler)
        m.add_controller(
            "task",
            "Task",
            self.task_reconciler,
            owns=["ToolCall"],
            workers=self.options.task_workers,
        )
        m.add_controller(
            "toolcall",
            "ToolCall",
            self.toolcall_reconciler,
            watches={"Task": map_owner("ToolCall")},
            workers=self.options.toolcall_workers,
        )

    async def start(self) -> None:
        if self.store_server is not None:
            self.store_server.start()
        await self.manager.start()
        self.metrics_exporter.start()

    async def stop(self) -> None:
        self.metrics_exporter.stop()
        await self.manager.stop()
        if self.store_server is not None:
            self.store_server.stop()
        await self.mcp_manager.close()
        closer = getattr(self.llm_factory, "aclose", None)
        if closer is not None:
            await closer()
        if self.rest_server is not None:
            await self.rest_server.stop()
        self.store.close()


async def serve_until_signalled() -> None:
    """Block until SIGTERM/SIGINT (docker-stop/systemd/Ctrl-C). Handlers are
    REMOVED once the signal arrives, so a second signal during a hung
    cleanup still kills the process instead of being swallowed."""
    import signal

    done = asyncio.Event()
    loop = asyncio.get_running_loop()
    installed: list = []
    for sig in (signal.SIGTERM, signal.SIGINT):
        try:
            loop.add_signal_handler(sig, done.set)
            installed.append(sig)
        except (NotImplementedError, RuntimeError):
            pass  # non-main thread / platform without signal support
    try:
        await done.wait()
    finally:
        for sig in installed:
            loop.remove_signal_handler(sig)


async def run_operator(options: OperatorOptions) -> None:
    """Blocking entrypoint (the ``mgr.Start`` equivalent): serves until
    signalled, then shuts everything down cleanly (controllers, MCP
    subprocesses, sqlite, REST, and the TPU engine if configured)."""
    op = Operator(options)
    await op.start()
    try:
        await serve_until_signalled()
    finally:
        await op.stop()
        if options.fleet is not None:
            options.fleet.stop(stop_engines=True)  # type: ignore[attr-defined]
        engine = options.engine
        if engine is not None:
            engine.stop()  # type: ignore[attr-defined]
